# Canonical entry points for builders and CI. `make verify` is THE
# command a checker runs: it installs the dev extras (pytest +
# hypothesis — the property suites importorskip cleanly when absent,
# but a verified build should run them) and then executes the exact
# tier-1 command from ROADMAP.md, byte for byte, so local runs and CI
# never drift from what the roadmap promises.

SHELL := /bin/bash

.PHONY: verify tier1 dev-install test bench bench-redelivery bench-fleet bench-federation bench-catchup bench-gossip bench-reactor bench-chaos bench-liveness bench-churn bench-device-verify bench-slo-overhead bench-profile-overhead bench-regress fleet-smoke federation-smoke catchup-smoke gossip-smoke chaos-smoke liveness-smoke churn-smoke metrics-smoke trace-smoke federation-scrape-smoke slo-overhead-smoke profile-overhead-smoke profile-smoke smoke obs-smoke

dev-install:
	python -m pip install -e '.[dev]'

# The exact ROADMAP.md "Tier-1 verify" command (keep in sync — that file
# is the source of truth; this target only gives it a stable name).
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

verify: dev-install tier1

# Fast local loop (no install, no log artifact).
test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

bench:
	python bench.py

# Amortized-verification bench: gossip redelivery + incremental chain
# growth, cache-on vs cache-off, real ECDSA (host-substrate sessions, so
# it runs identically under JAX_PLATFORMS=cpu). The fast tier-1 smoke for
# the same paths is tests/test_redelivery.py (stub signer).
bench-redelivery:
	python bench.py redelivery

# Scope-sharded fleet bench: aggregate votes/sec across all local
# devices, per-shard breakdown, paired fleet-vs-single-shard A/B with a
# machine-readable noise_verdict, and a MULTICHIP-compatible record.
bench-fleet:
	python bench.py fleet

# CI short run: 2 simulated shards on virtual CPU devices — exercises
# fleet routing, the psum tally path, and the sweep on every PR.
fleet-smoke:
	JAX_PLATFORMS=cpu python bench.py fleet --smoke

# Federated multi-host bench: N OS processes (one FleetGroup each —
# examples/federation_host.py), two-level (host, shard) placement,
# cross-host vote routing over coalesced OP_VOTE_BATCH frames, fabric
# OP_FLEET_TALLY tallies, paired federated-vs-single-host A/B with a
# machine-readable noise_verdict, and a LIVE SHARD MIGRATION under
# sustained traffic (freeze -> snapshot+tail adopt -> fingerprint
# equality -> atomic flip -> tail replay) with zero-lost-votes and
# zero-lost-decisions asserts. HOSTS=N picks the host count.
HOSTS ?= 2
bench-federation:
	JAX_PLATFORMS=cpu python bench.py fleet --hosts $(HOSTS)

# CI short run: 2 OS processes on CPU, tiny shapes, one migration —
# the whole federation surface (remote routing, tallies, migration,
# typed retry-after window) on every PR.
federation-smoke:
	JAX_PLATFORMS=cpu python bench.py fleet --hosts 2 --smoke

# State-sync catch-up bench: snapshot+tail vs full WAL replay at several
# history lengths, paired same-window A/B with a machine-readable
# noise_verdict, per-rep byte-identical convergence asserts.
bench-catchup:
	python bench.py catchup

# CI short run: two in-process peers over a real bridge, small signed
# history, snapshot+tail AND full-replay joiners both asserted
# byte-identical to the source, interrupted-transfer resume included.
catchup-smoke:
	JAX_PLATFORMS=cpu python examples/catchup_smoke.py

# Networked gossip bench: N peers as separate OS processes over real TCP
# (plus the shared-memory ring lane for the co-located case), aggregate
# networked votes/sec, paired same-window A/B against the serial
# BridgeClient loop with a machine-readable noise_verdict, per-rep
# cross-peer state_fingerprint equality asserts, and per-rep wire-path
# stage attribution (decode / crypto / device-apply seconds). STAGES=1
# passes --stages explicitly; STAGES=0 drops the attribution block.
STAGES ?= 1
bench-gossip:
	python bench.py gossip $(if $(filter 0,$(STAGES)),--no-stages,--stages)

# Apply-reactor A/B bench: ONLY the paired reactor-off/on fabric arms on
# dedicated peer sets (the reactor pinned per arm via gossip_peer.py
# --reactor), gossip-frame-sized coalescer windows so the workload sits
# in the many-small-dispatches regime the reactor amortizes. Reports a
# noise_verdict, votes_per_dispatch per arm, and each arm's device-apply
# share of server busy time vs the r06 66.8% attribution.
bench-reactor:
	python bench.py gossip --reactor-only

# CI short run: 3 in-process peers — pipelining + coalescing + the
# zero-copy columnar OP_VOTE_BATCH server path + a sampled-fanout
# divergence healed by ONE anti-entropy round, final state
# fingerprint-identical across peers. CI runs it twice: native parser
# available, and HASHGRAPH_TPU_WIRE_COLUMNAR=0 forcing the pure-Python
# object fallback path (which must stay green on its own).
gossip-smoke:
	JAX_PLATFORMS=cpu python bench.py gossip --smoke

# Deterministic chaos harness, full depth: the scenario corpus
# (partitions incl. asymmetric, drop/dup/reorder storms, kill-9
# crash-restart via WAL recovery, lost-disk catch-up, equivocators,
# forkers, expired-spam + signature-burst, liveness adversities) at 5
# pinned seeds, four machine-checked verdicts per run (convergence,
# exact-culprit accountability, honest-decision safety, liveness) + the
# blindness self-test.
bench-chaos:
	JAX_PLATFORMS=cpu python bench.py chaos

# CI short run: the same corpus at 3 pinned seeds. Seed-deterministic:
# a failure here is a reproducible regression (re-run the same seed),
# never a flake. The JSON line carries the machine-readable
# `scenarios: {passed, failed, seeds}` block.
chaos-smoke:
	JAX_PLATFORMS=cpu python bench.py chaos --smoke

# Liveness observatory, full depth: the Chandra–Toueg adversity trio
# (flapping-links, slow-never-dead, stale-partial-synchrony) at 5 pinned
# seeds with the φ-accrual A/B hard-gated — the adaptive watchdog must
# suspect every flap the binary floor misses, zero stale convictions may
# survive the heal in EITHER arm, and the tight-static counterfactual
# must convict the slow-but-alive peer on every seed.
bench-liveness:
	JAX_PLATFORMS=cpu python bench.py liveness

# CI short run: the same battery + A/B gates at 3 pinned seeds.
# Seed-deterministic — a failure reproduces exactly from the seed in
# the log, never a flake.
liveness-smoke:
	JAX_PLATFORMS=cpu python bench.py liveness --smoke

# Tiered-session-lifecycle churn bench: 10M+ cumulative sessions through
# a fixed-size engine with per-wave asserted RSS + device-slot + tier
# ceilings (demote -> demand-page -> GC), paired same-window A/B against
# an untier'd delete_scope arm with a machine-readable noise_verdict.
bench-churn:
	JAX_PLATFORMS=cpu python bench.py churn

# CI short run: the same lifecycle (ceiling asserts ON, A/B included) at
# a bounded cumulative-session count.
churn-smoke:
	JAX_PLATFORMS=cpu python bench.py churn --smoke

# Device-vs-host-pool Ed25519 batch-verify A/B (the crypto_device
# subsystem): same signed corpus through both verify_batch backends,
# interleaved reps at 256/1k/4k/16k (SMOKE=1: 256/1k for CI), per-phase
# device timings (decompress/SHA-512/MSM) and a machine-readable
# noise_verdict that names the winner honestly — on CPU backends the
# native pool wins; the device path is for accelerator hardware. The
# persistent XLA compile cache (bench.py's default) keeps recompiles
# from dominating repeat runs.
SMOKE ?= 0
bench-device-verify:
	python bench.py device-verify $(if $(filter 1,$(SMOKE)),--smoke,)

# End-to-end observability check: start a bridge server (WAL + HTTP
# sidecar), drive a proposal to decision, scrape /metrics + /healthz and
# the GET_METRICS opcode, and assert the well-known metric families are
# present. See examples/metrics_smoke.py.
metrics-smoke:
	JAX_PLATFORMS=cpu python examples/metrics_smoke.py

# End-to-end distributed-tracing check: two bridge peers decide one
# proposal with trace context on the wire; per-peer span dumps stitch
# into one Chrome/Perfetto trace (shared trace_id, causal order) and
# EXPLAIN reports the quorum arithmetic. See examples/trace_smoke.py.
trace-smoke:
	JAX_PLATFORMS=cpu python examples/trace_smoke.py

# Metric-federation check: 2 federation hosts as OS processes, one
# decision each, then OP_METRICS_PULL frames merged into ONE scrape —
# both hosts' families labelled host="...", fleet-total bare series,
# merged /slo rollup — served over a real HTTP sidecar. See
# examples/federation_scrape_smoke.py.
federation-scrape-smoke:
	JAX_PLATFORMS=cpu python examples/federation_scrape_smoke.py

# Always-on SLO tracking cost: paired interleaved A/B (SLO engine
# enabled vs disabled) on a decision-heavy workload; the verdict holds
# the median overhead under the 5% acceptance bar, noise-aware.
bench-slo-overhead:
	JAX_PLATFORMS=cpu python bench.py slo-overhead

# CI short run of the same A/B at tiny shapes.
slo-overhead-smoke:
	JAX_PLATFORMS=cpu python bench.py slo-overhead --smoke

# Always-on continuous-profiler cost: paired interleaved A/B (sampler
# enabled vs parked, thread alive in both arms) on the same
# decision-heavy workload; the verdict holds the median overhead under
# the 2% acceptance bar, noise-aware.
bench-profile-overhead:
	JAX_PLATFORMS=cpu python bench.py profile-overhead

# CI short run of the same A/B at tiny shapes.
profile-overhead-smoke:
	JAX_PLATFORMS=cpu python bench.py profile-overhead --smoke

# End-to-end continuous-profiling check: the gossip smoke with the
# always-on sampler armed via the env opt-in — every peer serves
# OP_PROFILE, the bench merges the frames via merge_profile_states and
# asserts the stage shares in-run (known names, sum <= 1.0).
profile-smoke:
	HASHGRAPH_TPU_PROFILE=1 JAX_PLATFORMS=cpu python bench.py gossip --smoke

# Perf-regression sentry: reconstruct the BENCH_*.json trajectory and
# issue noise-aware verdicts — exit 1 on a confident regression; drops
# the recorded spreads cannot distinguish from noise stay advisory.
# (`python bench.py regress` emits the same verdict as a bench line.)
bench-regress:
	python tools/bench_regress.py

# Aggregate observability smoke: single-process scrape + trace paths.
smoke: metrics-smoke trace-smoke

# Fleet-wide observability plane smoke: everything `smoke` covers plus
# the federated merged scrape and the SLO-overhead A/B — the CI
# `obs-smoke` job's target.
obs-smoke: smoke federation-scrape-smoke slo-overhead-smoke
