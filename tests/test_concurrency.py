"""Concurrency behavior (reference: tests/concurrency_tests.rs): parallel
voters against a shared service must serialize correctly."""

import threading

from hashgraph_tpu import CreateProposalRequest, ConsensusConfig, build_vote
from hashgraph_tpu.errors import ConsensusError, DuplicateVote

from common import NOW, make_service, random_stub_signer, sibling_service

SCOPE = "concurrency_scope"


def test_parallel_voters_all_succeed():
    """reference: tests/concurrency_tests.rs:44-99 — 10 distinct voters race;
    all succeed."""
    service = make_service()
    request = CreateProposalRequest(
        name="Concurrent",
        payload=b"",
        proposal_owner=service.signer().identity(),
        expected_voters_count=30,  # high n so consensus can't close the session early
        expiration_timestamp=120,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal_with_config(
        SCOPE, request, ConsensusConfig.gossipsub(), NOW
    )

    n_threads = 10
    barrier = threading.Barrier(n_threads)
    errors = []

    def vote_thread():
        peer = sibling_service(service)
        barrier.wait()
        try:
            peer.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
        except ConsensusError as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=vote_thread) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert errors == []
    stored = service.storage().get_proposal(SCOPE, proposal.proposal_id)
    assert len(stored.votes) == n_threads


def test_parallel_proposal_creation():
    """reference: tests/concurrency_tests.rs:103-142"""
    service = make_service(max_sessions=100)
    barrier = threading.Barrier(8)
    ids = []
    lock = threading.Lock()

    def create_thread(i):
        request = CreateProposalRequest(
            name=f"p{i}",
            payload=b"",
            proposal_owner=random_stub_signer().identity(),
            expected_voters_count=3,
            expiration_timestamp=120,
            liveness_criteria_yes=True,
        )
        barrier.wait()
        proposal = service.create_proposal(SCOPE, request, NOW)
        with lock:
            ids.append(proposal.proposal_id)

    threads = [threading.Thread(target=create_thread, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert len(set(ids)) == 8
    sessions = service.storage().list_scope_sessions(SCOPE)
    assert len(sessions) == 8


def test_same_voter_race_single_success():
    """reference: tests/concurrency_tests.rs:146-228 — 5 threads with the SAME
    identity racing: exactly 1 success, 4 duplicate errors."""
    service = make_service()
    request = CreateProposalRequest(
        name="Race",
        payload=b"",
        proposal_owner=service.signer().identity(),
        expected_voters_count=30,
        expiration_timestamp=120,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal_with_config(
        SCOPE, request, ConsensusConfig.gossipsub(), NOW
    )

    racer = random_stub_signer()
    n_threads = 5
    barrier = threading.Barrier(n_threads)
    outcomes = []
    lock = threading.Lock()

    def race_thread():
        # Each thread builds its own vote from the pre-vote snapshot and
        # delivers it; the in-lock duplicate check must let exactly one in.
        snapshot = service.storage().get_proposal(SCOPE, proposal.proposal_id)
        vote = build_vote(snapshot, True, racer, NOW)
        barrier.wait()
        try:
            service.process_incoming_vote(SCOPE, vote, NOW)
            result = "ok"
        except DuplicateVote:
            result = "duplicate"
        with lock:
            outcomes.append(result)

    threads = [threading.Thread(target=race_thread) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert outcomes.count("ok") == 1
    assert outcomes.count("duplicate") == n_threads - 1
    stored = service.storage().get_proposal(SCOPE, proposal.proposal_id)
    assert len(stored.votes) == 1
