"""Wire codec tests: roundtrips, proto3 default omission, and byte-level
compatibility with the canonical protobuf runtime (standing in for prost)."""

import pytest

from hashgraph_tpu.wire import Proposal, Vote


def full_vote() -> Vote:
    return Vote(
        vote_id=0xDEADBEEF,
        vote_owner=b"\x01" * 20,
        proposal_id=42,
        timestamp=1_700_000_000,
        vote=True,
        parent_hash=b"p" * 32,
        received_hash=b"r" * 32,
        vote_hash=b"h" * 32,
        signature=b"s" * 65,
    )


def full_proposal() -> Proposal:
    return Proposal(
        name="upgrade-v2",
        payload=b"\x00\x01\x02",
        proposal_id=7,
        proposal_owner=b"\x02" * 20,
        votes=[full_vote(), Vote(vote_id=1, vote_owner=b"x", proposal_id=7)],
        expected_voters_count=5,
        round=2,
        timestamp=1_700_000_000,
        expiration_timestamp=1_700_000_060,
        liveness_criteria_yes=True,
    )


class TestRoundtrip:
    def test_vote_roundtrip(self):
        v = full_vote()
        assert Vote.decode(v.encode()) == v

    def test_proposal_roundtrip(self):
        p = full_proposal()
        assert Proposal.decode(p.encode()) == p

    def test_default_messages_encode_empty(self):
        # proto3: all-default messages serialize to zero bytes.
        assert Vote().encode() == b""
        assert Proposal().encode() == b""
        assert Vote.decode(b"") == Vote()

    def test_false_vote_is_omitted(self):
        v = Vote(vote_id=1, vote=False)
        raw = v.encode()
        # field 24 (vote) must not appear when false
        assert (24 << 3) | 0 not in raw
        assert Vote.decode(raw).vote is False

    def test_signing_payload_blanks_signature_only(self):
        v = full_vote()
        blanked = v.clone()
        blanked.signature = b""
        assert v.signing_payload() == blanked.encode()

    def test_u64_max_timestamp(self):
        v = Vote(timestamp=2**64 - 1)
        assert Vote.decode(v.encode()).timestamp == 2**64 - 1

    def test_unknown_fields_skipped(self):
        # A field number we never use (5, varint) must be skipped on decode.
        extra = bytes([(5 << 3) | 0, 0x05]) + full_vote().encode()
        assert Vote.decode(extra) == full_vote()

    def test_encode_split_parity(self):
        # head + <field 12> + tail must equal encode() byte for byte for
        # any vote-free proposal — the bulk-demotion template contract.
        from hashgraph_tpu.wire import _encode_uint_field

        p = full_proposal()
        p.votes = []
        for pid in (0, 1, 127, 128, 2**31, 2**32 - 1):
            p.proposal_id = pid
            head, tail = p.encode_split()
            buf = bytearray(head)
            _encode_uint_field(buf, 12, pid)
            assert bytes(buf) + tail == p.encode()
        sparse = Proposal(name="", payload=b"", proposal_id=9)
        head, tail = sparse.encode_split()
        buf = bytearray(head)
        _encode_uint_field(buf, 12, 9)
        assert bytes(buf) + tail == sparse.encode()

    def test_encode_split_rejects_embedded_votes(self):
        with pytest.raises(ValueError):
            full_proposal().encode_split()


class TestProstCompatibility:
    """Encode with google.protobuf against the same schema and compare bytes.

    prost and the canonical runtime both emit proto3 fields in ascending
    field-number order with defaults omitted, so byte equality here implies
    byte compatibility with the reference
    (schema: reference src/protos/messages/v1/consensus.proto:5-29).
    """

    @pytest.fixture(scope="class")
    def pb_classes(self):
        pool_mod = pytest.importorskip("google.protobuf.descriptor_pool")
        from google.protobuf import descriptor_pb2, message_factory

        fd = descriptor_pb2.FileDescriptorProto()
        fd.name = "consensus_compat.proto"
        fd.package = "consensus.v1"
        fd.syntax = "proto3"

        vote = fd.message_type.add()
        vote.name = "Vote"
        for num, fname, ftype in [
            (20, "vote_id", descriptor_pb2.FieldDescriptorProto.TYPE_UINT32),
            (21, "vote_owner", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
            (22, "proposal_id", descriptor_pb2.FieldDescriptorProto.TYPE_UINT32),
            (23, "timestamp", descriptor_pb2.FieldDescriptorProto.TYPE_UINT64),
            (24, "vote", descriptor_pb2.FieldDescriptorProto.TYPE_BOOL),
            (25, "parent_hash", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
            (26, "received_hash", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
            (27, "vote_hash", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
            (28, "signature", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
        ]:
            f = vote.field.add()
            f.name, f.number, f.type = fname, num, ftype
            f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

        prop = fd.message_type.add()
        prop.name = "Proposal"
        for num, fname, ftype, extra in [
            (10, "name", descriptor_pb2.FieldDescriptorProto.TYPE_STRING, None),
            (11, "payload", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES, None),
            (12, "proposal_id", descriptor_pb2.FieldDescriptorProto.TYPE_UINT32, None),
            (13, "proposal_owner", descriptor_pb2.FieldDescriptorProto.TYPE_BYTES, None),
            (14, "votes", descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE, ".consensus.v1.Vote"),
            (15, "expected_voters_count", descriptor_pb2.FieldDescriptorProto.TYPE_UINT32, None),
            (16, "round", descriptor_pb2.FieldDescriptorProto.TYPE_UINT32, None),
            (17, "timestamp", descriptor_pb2.FieldDescriptorProto.TYPE_UINT64, None),
            (18, "expiration_timestamp", descriptor_pb2.FieldDescriptorProto.TYPE_UINT64, None),
            (19, "liveness_criteria_yes", descriptor_pb2.FieldDescriptorProto.TYPE_BOOL, None),
        ]:
            f = prop.field.add()
            f.name, f.number, f.type = fname, num, ftype
            if fname == "votes":
                f.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
                f.type_name = extra
            else:
                f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

        pool = pool_mod.DescriptorPool()
        pool.Add(fd)
        msgs = message_factory.GetMessageClassesForFiles(["consensus_compat.proto"], pool)
        return msgs["consensus.v1.Vote"], msgs["consensus.v1.Proposal"]

    def _pb_vote(self, PbVote, v: Vote):
        m = PbVote()
        m.vote_id = v.vote_id
        m.vote_owner = v.vote_owner
        m.proposal_id = v.proposal_id
        m.timestamp = v.timestamp
        m.vote = v.vote
        m.parent_hash = v.parent_hash
        m.received_hash = v.received_hash
        m.vote_hash = v.vote_hash
        m.signature = v.signature
        return m

    def test_vote_bytes_match(self, pb_classes):
        PbVote, _ = pb_classes
        for v in [full_vote(), Vote(), Vote(vote_id=1), Vote(vote=True, timestamp=2**63)]:
            assert v.encode() == self._pb_vote(PbVote, v).SerializeToString()

    def test_proposal_bytes_match(self, pb_classes):
        PbVote, PbProposal = pb_classes
        p = full_proposal()
        m = PbProposal()
        m.name = p.name
        m.payload = p.payload
        m.proposal_id = p.proposal_id
        m.proposal_owner = p.proposal_owner
        for v in p.votes:
            m.votes.append(self._pb_vote(PbVote, v))
        m.expected_voters_count = p.expected_voters_count
        m.round = p.round
        m.timestamp = p.timestamp
        m.expiration_timestamp = p.expiration_timestamp
        m.liveness_criteria_yes = p.liveness_criteria_yes
        assert p.encode() == m.SerializeToString()

    def test_decode_canonical_bytes(self, pb_classes):
        PbVote, _ = pb_classes
        v = full_vote()
        assert Vote.decode(self._pb_vote(PbVote, v).SerializeToString()) == v
