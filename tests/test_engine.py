"""TpuConsensusEngine end-to-end: service-parity, batch ingest, timeouts.

The engine must be observably identical to the scalar ConsensusService — the
same API calls with the same inputs produce the same results, errors, events,
and stored state (the bit-exactness bar from SURVEY §6). The strongest test
here drives randomized mixed traces through both side by side.
"""

import numpy as np
import pytest

from hashgraph_tpu import (
    BroadcastEventBus,
    ConsensusConfig,
    ConsensusError,
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
    InsufficientVotesAtTimeout,
    NetworkType,
    ProposalAlreadyExist,
    ProposalExpired,
    SessionNotFound,
    StatusCode,
    UserAlreadyVoted,
    build_vote,
)
from hashgraph_tpu.engine import ProposalPool, TpuConsensusEngine
from hashgraph_tpu.errors import VoterCapacityExceeded

from common import NOW, make_service, random_stub_signer


def make_engine(**kw) -> TpuConsensusEngine:
    kw.setdefault("capacity", 64)
    kw.setdefault("voter_capacity", 16)
    return TpuConsensusEngine(random_stub_signer(), **kw)


def request(n=3, name="prop", exp=1000, liveness=True) -> CreateProposalRequest:
    return CreateProposalRequest(
        name=name,
        payload=b"payload",
        proposal_owner=b"owner",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


def drain(receiver):
    events = []
    while (item := receiver.try_recv()) is not None:
        events.append(item)
    return events


class TestEngineBasicFlow:
    def test_quickstart_three_voters(self):
        """README quick-start: 3 voters, gossipsub, 2/3 — two YES decide."""
        engine = make_engine()
        receiver = engine.event_bus().subscribe()
        proposal = engine.create_proposal("s", request(3), NOW)
        pid = proposal.proposal_id

        engine.cast_vote("s", pid, True, NOW)
        assert engine.get_consensus_result("s", pid) is None

        remote = random_stub_signer()
        vote = build_vote(engine.get_proposal("s", pid), True, remote, NOW)
        engine.process_incoming_vote("s", vote, NOW)

        assert engine.get_consensus_result("s", pid) is True
        events = drain(receiver)
        assert events == [("s", ConsensusReached(pid, True, NOW))]

    def test_cast_vote_twice_rejected(self):
        engine = make_engine()
        pid = engine.create_proposal("s", request(3), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        with pytest.raises(UserAlreadyVoted):
            engine.cast_vote("s", pid, False, NOW)

    def test_unknown_session(self):
        engine = make_engine()
        with pytest.raises(SessionNotFound):
            engine.cast_vote("s", 42, True, NOW)
        with pytest.raises(SessionNotFound):
            engine.handle_consensus_timeout("s", 42, NOW)

    def test_expired_proposal_rejects_cast(self):
        engine = make_engine()
        pid = engine.create_proposal("s", request(3, exp=10), NOW).proposal_id
        with pytest.raises(ProposalExpired):
            engine.cast_vote("s", pid, True, NOW + 10)

    def test_duplicate_incoming_proposal(self):
        engine = make_engine()
        proposal = engine.create_proposal("s", request(3), NOW)
        with pytest.raises(ProposalAlreadyExist):
            engine.process_incoming_proposal("s", proposal, NOW)

    def test_scope_isolation(self):
        engine = make_engine()
        pid_a = engine.create_proposal("a", request(3), NOW).proposal_id
        pid_b = engine.create_proposal("b", request(3), NOW).proposal_id
        engine.cast_vote("a", pid_a, True, NOW)
        with pytest.raises(SessionNotFound):
            engine.get_proposal("b", pid_a) if pid_a != pid_b else (_ for _ in ()).throw(
                SessionNotFound()
            )
        assert engine.get_scope_stats("a").total_sessions == 1
        assert engine.get_scope_stats("b").total_sessions == 1


class TestEngineIncomingProposal:
    def test_embedded_votes_replayed(self):
        """A proposal gossiped with its vote chain loads at the right tally."""
        origin = make_engine()
        proposal = origin.create_proposal("s", request(3), NOW)
        pid = proposal.proposal_id
        origin.cast_vote("s", pid, True, NOW)
        carried = origin.get_proposal("s", pid)

        receiver_engine = make_engine()
        receiver_engine.process_incoming_proposal("s", carried, NOW)
        # One more YES decides (2/3 of 3 = 2).
        receiver_engine.cast_vote("s", pid, True, NOW)
        assert receiver_engine.get_consensus_result("s", pid) is True

    def test_already_decided_chain_emits_event_on_load(self):
        origin = make_engine()
        pid = origin.create_proposal("s", request(3), NOW).proposal_id
        origin.cast_vote("s", pid, True, NOW)
        v = build_vote(origin.get_proposal("s", pid), True, random_stub_signer(), NOW)
        origin.process_incoming_vote("s", v, NOW)
        carried = origin.get_proposal("s", pid)
        assert origin.get_consensus_result("s", pid) is True

        engine = make_engine()
        receiver = engine.event_bus().subscribe()
        engine.process_incoming_proposal("s", carried, NOW)
        assert engine.get_consensus_result("s", pid) is True
        assert drain(receiver) == [("s", ConsensusReached(pid, True, NOW))]


class TestEngineTimeouts:
    def _p2p_engine(self):
        engine = make_engine()
        engine.scope("s").with_network_type(NetworkType.P2P).initialize()
        return engine

    def test_timeout_reaches_with_liveness_yes(self):
        """2 of 5 voted YES; liveness fills 3 silent as YES at timeout."""
        engine = make_engine()
        receiver = engine.event_bus().subscribe()
        pid = engine.create_proposal("s", request(5, liveness=True), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        v = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        engine.process_incoming_vote("s", v, NOW)

        result = engine.handle_consensus_timeout("s", pid, NOW + 100)
        assert result is True
        assert ("s", ConsensusReached(pid, True, NOW + 100)) in drain(receiver)

    def test_timeout_no_result(self):
        """liveness=False: 1 YES + 4 silent-as-NO -> NO at timeout."""
        engine = make_engine()
        pid = engine.create_proposal("s", request(5, liveness=False), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        assert engine.handle_consensus_timeout("s", pid, NOW + 100) is False

    def test_timeout_tie_fails(self):
        """n=4, 2 yes 2 no, full participation would tie-break — but with
        only 2 votes and liveness filling both ways we can craft a genuine
        insufficient case: n=2 would be unanimity, so use threshold 1.0."""
        engine = make_engine()
        engine.scope("s").with_threshold(1.0).initialize()
        pid = engine.create_proposal("s", request(4, liveness=True), NOW).proposal_id
        receiver = engine.event_bus().subscribe()
        # 2 YES, 2 NO from four voters: yes_w = 2, no_w = 2, tot==n -> tie ->
        # liveness YES. For a FAILED outcome use liveness=False and a split
        # that reaches neither bar: threshold 1.0 means req=4.
        signers = [random_stub_signer() for _ in range(2)]
        for i, signer in enumerate(signers):
            v = build_vote(engine.get_proposal("s", pid), i % 2 == 0, signer, NOW)
            engine.process_incoming_vote("s", v, NOW)
        with pytest.raises(InsufficientVotesAtTimeout):
            engine.handle_consensus_timeout("s", pid, NOW + 100)
        assert ("s", ConsensusFailedEvent(pid, NOW + 100)) in drain(receiver)

    def test_timeout_idempotent_after_reached(self):
        engine = make_engine()
        pid = engine.create_proposal("s", request(3), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        v = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        engine.process_incoming_vote("s", v, NOW)
        assert engine.handle_consensus_timeout("s", pid, NOW + 100) is True
        assert engine.handle_consensus_timeout("s", pid, NOW + 200) is True

    def test_sweep_timeouts(self):
        engine = make_engine()
        pid_a = engine.create_proposal("s", request(5, exp=50), NOW).proposal_id
        pid_b = engine.create_proposal("s", request(5, exp=5000), NOW).proposal_id
        engine.cast_vote("s", pid_a, True, NOW)
        engine.cast_vote("s", pid_b, True, NOW)

        swept = engine.sweep_timeouts(NOW + 100)
        assert ("s", pid_a, True) in swept  # liveness fills YES
        assert all(pid != pid_b for _, pid, _ in swept)  # not yet expired
        assert engine.get_consensus_result("s", pid_b) is None


class TestEngineBatchIngest:
    def test_batch_across_sessions_and_scopes(self):
        engine = make_engine()
        pids = {}
        for scope in ("a", "b"):
            pids[scope] = engine.create_proposal(scope, request(3), NOW).proposal_id

        items = []
        for scope in ("a", "b"):
            for _ in range(2):
                vote = build_vote(
                    engine.get_proposal(scope, pids[scope]),
                    True,
                    random_stub_signer(),
                    NOW,
                )
                # Build sequentially so received_hash chains stay valid:
                # apply each vote before building the next.
                engine.process_incoming_vote(scope, vote, NOW)

        assert engine.get_consensus_result("a", pids["a"]) is True
        assert engine.get_consensus_result("b", pids["b"]) is True

    def test_batch_statuses_unknown_and_invalid(self):
        engine = make_engine()
        pid = engine.create_proposal("s", request(3), NOW).proposal_id
        good = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        forged = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        forged.signature = bytes(len(forged.signature))
        unknown = good.clone()
        unknown.proposal_id = pid ^ 0xFFFF

        statuses = engine.ingest_votes(
            [("s", good), ("s", forged), ("s", unknown)], NOW
        )
        assert statuses[0] == int(StatusCode.OK)
        assert statuses[1] == int(StatusCode.INVALID_VOTE_SIGNATURE)
        assert statuses[2] == int(StatusCode.SESSION_NOT_FOUND)

    def test_ethereum_batched_verification_path(self):
        """Multi-vote batches route signature checks through the scheme's
        verify_batch (native-accelerated for Ethereum); statuses must match
        the scalar error precedence exactly."""
        from hashgraph_tpu import EthereumConsensusSigner

        engine = TpuConsensusEngine(
            EthereumConsensusSigner.random(), capacity=8, voter_capacity=8
        )
        pid = engine.create_proposal("s", request(5, liveness=False), NOW).proposal_id
        voters = [EthereumConsensusSigner.random() for _ in range(3)]
        good0 = build_vote(engine.get_proposal("s", pid), True, voters[0], NOW)
        engine.process_incoming_vote("s", good0, NOW)

        base = engine.get_proposal("s", pid)
        good1 = build_vote(base, False, voters[1], NOW)
        forged = build_vote(base, True, voters[2], NOW)
        # Flip a bit in r: recovery yields a different address (or fails).
        forged.signature = bytes([forged.signature[0] ^ 1]) + forged.signature[1:]
        short = build_vote(base, True, voters[2], NOW)
        short.signature = short.signature[:10]
        unsigned = build_vote(base, True, voters[2], NOW)
        unsigned.signature = b""

        statuses = engine.ingest_votes(
            [("s", good1), ("s", forged), ("s", short), ("s", unsigned)], NOW
        )
        assert statuses[0] == int(StatusCode.OK)
        assert statuses[1] in (
            int(StatusCode.INVALID_VOTE_SIGNATURE),
            int(StatusCode.SIGNATURE_SCHEME),
        )
        assert statuses[2] == int(StatusCode.SIGNATURE_SCHEME)  # bad length
        assert statuses[3] == int(StatusCode.EMPTY_SIGNATURE)  # structural first

    def test_voter_capacity_exhaustion(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=4, voter_capacity=4
        )
        # Gossipsub accepts any number of distinct voters; lanes are the
        # engine's physical bound.
        pid = engine.create_proposal("s", request(4, liveness=False), NOW).proposal_id
        statuses = []
        for i in range(5):
            vote = build_vote(
                engine.get_proposal("s", pid),
                False,
                random_stub_signer(),
                NOW,
            )
            statuses.append(engine.ingest_votes([("s", vote)], NOW)[0])
        assert statuses[:4] == [int(StatusCode.OK)] * 3 + [int(StatusCode.ALREADY_REACHED)]
        # 4th distinct voter hit ALREADY_REACHED (3 NO of 4 decided NO), the
        # 5th never got a lane but the session being decided wins precedence
        # in the scalar semantics; force the capacity error on an active one.
        engine2 = TpuConsensusEngine(
            random_stub_signer(), capacity=4, voter_capacity=3
        )
        engine2.scope("s").with_threshold(1.0).initialize()
        pid2 = engine2.create_proposal(
            "s", request(3, liveness=False), NOW
        ).proposal_id
        # Y, N, N at threshold 1.0 (req=3): neither side reaches the bar and
        # there is no tie, so the session stays ACTIVE with all lanes taken.
        for i in range(3):
            vote = build_vote(
                engine2.get_proposal("s", pid2), i == 0, random_stub_signer(), NOW
            )
            assert engine2.ingest_votes([("s", vote)], NOW)[0] == int(StatusCode.OK)
        extra = build_vote(
            engine2.get_proposal("s", pid2), True, random_stub_signer(), NOW
        )
        assert engine2.ingest_votes([("s", extra)], NOW)[0] == int(
            StatusCode.VOTER_CAPACITY_EXCEEDED
        )
        with pytest.raises(VoterCapacityExceeded):
            engine2.process_incoming_vote("s", extra, NOW)


class TestEngineLifecycle:
    def test_eviction_beyond_scope_cap(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=4,
            max_sessions_per_scope=2,
        )
        pids = [
            engine.create_proposal("s", request(3, name=f"p{i}"), NOW + i).proposal_id
            for i in range(4)
        ]
        stats = engine.get_scope_stats("s")
        assert stats.total_sessions == 2
        # Newest two survive.
        assert engine.get_proposal("s", pids[3]) is not None
        assert engine.get_proposal("s", pids[2]) is not None
        with pytest.raises(SessionNotFound):
            engine.get_proposal("s", pids[0])
        # Evicted slots are reusable.
        assert engine.pool().free_slots == 6

    def test_pool_exhaustion_spills_to_host(self):
        # The reference service has no capacity limits (src/service.rs:86-97);
        # when the device pool is full the engine degrades to a host-backed
        # session instead of erroring (see test_engine_spill.py for the full
        # spilled-session lifecycle).
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=2, voter_capacity=4
        )
        engine.create_proposal("a", request(3), NOW)
        engine.create_proposal("b", request(3), NOW)
        pid = engine.create_proposal("c", request(3), NOW).proposal_id
        assert engine.pool().free_slots == 0
        assert engine.get_consensus_result("c", pid) is None
        assert engine.get_scope_stats("c").active_sessions == 1

    def test_delete_scope_frees_slots(self):
        engine = make_engine()
        for i in range(3):
            engine.create_proposal("s", request(3, name=f"p{i}"), NOW)
        engine.scope("s").with_network_type(NetworkType.P2P).initialize()
        engine.delete_scope("s")
        assert engine.get_scope_stats("s").total_sessions == 0
        assert engine.get_scope_config("s") is None
        assert engine.pool().free_slots == 64

    def test_export_session_roundtrip(self):
        engine = make_engine()
        pid = engine.create_proposal("s", request(3), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        session = engine.export_session("s", pid)
        assert session.state.is_active
        assert len(session.votes) == 1
        assert session.proposal.round == 2  # gossipsub round bump


class TestEngineServiceParity:
    """Randomized side-by-side traces: engine vs scalar service."""

    @pytest.mark.parametrize("seed", range(4))
    def test_random_trace_parity(self, seed):
        rng = np.random.default_rng(seed)
        service = make_service()
        engine = TpuConsensusEngine(
            service.signer(), capacity=64, voter_capacity=16,
        )
        service_rx = service.event_bus().subscribe()
        engine_rx = engine.event_bus().subscribe()

        # Shared voters with deterministic identities.
        voters = [random_stub_signer() for _ in range(8)]
        scopes = ["alpha", "beta"]
        for scope in scopes:
            if rng.random() < 0.5:
                service.scope(scope).with_network_type(NetworkType.P2P).initialize()
                engine.scope(scope).with_network_type(NetworkType.P2P).initialize()

        pids: list[tuple[str, int]] = []
        for step in range(60):
            now = NOW + step
            action = rng.random()
            if action < 0.2 or not pids:
                scope = scopes[int(rng.integers(len(scopes)))]
                n = int(rng.integers(2, 8))
                live = bool(rng.random() < 0.5)
                exp = int(rng.choice([30, 1000]))
                req_obj = CreateProposalRequest(
                    name=f"p{step}",
                    payload=b"x",
                    proposal_owner=b"o",
                    expected_voters_count=n,
                    expiration_timestamp=exp,
                    liveness_criteria_yes=live,
                )
                proposal = req_obj.into_proposal(now)
                # Drive both through process_incoming_proposal so they share
                # one proposal_id.
                s_exc = e_exc = None
                try:
                    service.process_incoming_proposal(scope, proposal.clone(), now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                try:
                    engine.process_incoming_proposal(scope, proposal.clone(), now)
                except ConsensusError as exc:
                    e_exc = type(exc)
                assert s_exc == e_exc, f"step {step} create: {s_exc} vs {e_exc}"
                if s_exc is None:
                    pids.append((scope, proposal.proposal_id))
            elif action < 0.85:
                scope, pid = pids[int(rng.integers(len(pids)))]
                signer = voters[int(rng.integers(len(voters)))]
                choice = bool(rng.random() < 0.6)
                s_exc = e_exc = None
                vote = None
                try:
                    base = service.storage().get_proposal(scope, pid)
                    vote = build_vote(base, choice, signer, now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                if vote is not None:
                    try:
                        service.process_incoming_vote(scope, vote.clone(), now)
                    except ConsensusError as exc:
                        s_exc = type(exc)
                    try:
                        engine.process_incoming_vote(scope, vote.clone(), now)
                    except ConsensusError as exc:
                        e_exc = type(exc)
                    assert s_exc == e_exc, (
                        f"step {step} vote: service={s_exc} engine={e_exc}"
                    )
            else:
                scope, pid = pids[int(rng.integers(len(pids)))]
                s_exc = e_exc = None
                s_res = e_res = None
                try:
                    s_res = service.handle_consensus_timeout(scope, pid, now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                try:
                    e_res = engine.handle_consensus_timeout(scope, pid, now)
                except ConsensusError as exc:
                    e_exc = type(exc)
                assert (s_res, s_exc) == (e_res, e_exc), f"step {step} timeout"

        # Final state parity for every session both sides still track.
        for scope, pid in pids:
            s_session = service.storage().get_session(scope, pid)
            if s_session is None:
                with pytest.raises(SessionNotFound):
                    engine.get_proposal(scope, pid)
                continue
            e_session = engine.export_session(scope, pid)
            assert e_session.state == s_session.state, f"{scope}/{pid} state"
            assert set(e_session.votes) == set(s_session.votes), f"{scope}/{pid} voters"
            assert e_session.proposal.round == s_session.proposal.round
            for owner, vote in s_session.votes.items():
                assert e_session.votes[owner].vote == vote.vote

        # Event streams match exactly (order and payloads).
        assert drain(service_rx) == drain(engine_rx)
