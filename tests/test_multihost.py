"""2-process jax.distributed integration: the MultiHostPool end-to-end.

Spawns two real Python processes, each contributing 2 virtual CPU devices
to one 4-device mesh, and drives the full multi-host contract: replicated
control plane (allocate/timeout), process-local vote ingest with agreed
grid shapes, psum global stats, and owner-only transition reporting. This
is the distributed-communication-backend check from SURVEY §2.3 — DCN-free
vote routing with consensus state sharded across hosts."""

import os
import socket
import subprocess
import sys

import pytest

# Shared bootstrap: 2 processes x 2 virtual CPU devices, one jax.distributed
# fleet, repo importable (spawned with cwd = repo root).
_PREAMBLE = r"""
import os, sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

process_id = int(sys.argv[1])
coordinator = sys.argv[2]

jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=process_id
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())
assert len(jax.local_devices()) == 2
sys.path.insert(0, os.getcwd())
"""

_WORKER = _PREAMBLE + r"""
from hashgraph_tpu.ops.decide import (
    STATE_ACTIVE,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    required_votes_np,
)
from hashgraph_tpu.parallel import MultiHostPool, distributed_consensus_mesh

NOW = 1_700_000_000
mesh = distributed_consensus_mesh()
pool = MultiHostPool(capacity_per_device=4, voter_capacity=8, mesh=mesh)
assert pool.capacity == 16
lo, hi = pool.local_slots()
assert (lo, hi) == ((0, 8) if process_id == 0 else (8, 16)), (lo, hi)

# Control plane: REPLICATED — identical allocation on both processes.
# 8 proposals, round-robin across the 4 devices: slots 0,4,8,12,1,5,9,13.
P = 8
slots = pool.allocate_batch(
    keys=[("s", i) for i in range(P)],
    n=np.full(P, 3),
    req=required_votes_np(np.full(P, 3), 2.0 / 3.0),
    cap=np.full(P, 2),
    gossip=np.ones(P, bool),
    liveness=np.full(P, True),
    expiry=np.array([NOW + (10_000 if i % 2 == 0 else 10) for i in range(P)]),
    created_at=np.full(P, NOW),
)
assert slots == [0, 4, 8, 12, 1, 5, 9, 13], slots

# Data plane: each process ingests votes ONLY for its own slots; cadence
# is collective (both processes dispatch twice).
mine = [s for s in slots if lo <= s < hi]
assert len(mine) == 4
statuses_seen = []
for lane in range(2):
    batch_slots = np.array(mine, np.int64)
    lanes = np.full(4, lane, np.int32)
    values = np.ones(4, bool)  # 2 YES of n=3 -> quorum 2 -> REACHED_YES
    pending = pool.ingest_async(batch_slots, lanes, values, NOW)
    statuses, transitions = pool.complete(pending)
    statuses_seen.append(list(statuses))
assert statuses_seen[0] == [0, 0, 0, 0], statuses_seen
assert statuses_seen[1] == [0, 0, 0, 0], statuses_seen
# Second lane decided every local session; transitions are local-only.
assert {s for s, _ in transitions} == set(mine)
assert all(st == STATE_REACHED_YES for _, st in transitions)

# Global stats via psum: every process sees the fleet-wide histogram.
counts = pool.global_state_counts()
assert counts[STATE_REACHED_YES] == 8, counts
assert counts[STATE_ACTIVE] == 0, counts

# Empty collective dispatch: process 1 has nothing this round but still
# participates (process 0 votes NO on nothing — both empty keeps it easy).
pending = pool.ingest_async(np.empty(0, np.int64), np.empty(0, np.int32), np.empty(0, bool), NOW)
st, tr = pool.complete(pending)
assert len(st) == 0 and tr == []

# Timeout sweep: REPLICATED args; each process gets back only its slots.
swept = pool.timeout(slots)
assert {s for s, _ in swept} == set(mine), swept
assert all(st == STATE_REACHED_YES for _, st in swept)  # idempotent: stays decided

print(f"MULTIHOST_OK p{process_id} slots={mine}")
"""


_ENGINE_WORKER = _PREAMBLE + r"""
from hashgraph_tpu import (
    CreateProposalRequest,
    Proposal,
    StatusCode,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import InsufficientVotesAtTimeout
from hashgraph_tpu.parallel import MultiHostPool, distributed_consensus_mesh

NOW = 1_700_000_000
mesh = distributed_consensus_mesh()
pool = MultiHostPool(capacity_per_device=4, voter_capacity=8, mesh=mesh)

# SPMD front-end fleet: identical engine on every process, one shared
# logical service. Control-plane calls run with identical args everywhere.
engine = TpuConsensusEngine(
    StubConsensusSigner(b"fleet-signer-00000000"[:20]),
    pool=pool,
    max_sessions_per_scope=64,
)
rx = engine.event_bus().subscribe()

def drain_pids(kind=None):
    out = []
    while (item := rx.try_recv()) is not None:
        if kind is None or type(item[1]).__name__ == kind:
            out.append(item[1].proposal_id)
    return out

def proposal(pid, n=3, expiry=10_000, liveness=True):
    return Proposal(
        name="p%d" % pid, payload=b"", proposal_id=pid, proposal_owner=b"o" * 20,
        votes=[], expected_voters_count=n, round=1, timestamp=NOW,
        expiration_timestamp=NOW + expiry, liveness_criteria_yes=liveness,
    )

# Control plane: 8 deterministic proposals registered identically.
P = 8
pids = [1000 + i for i in range(P)]
for pid in pids:
    engine.process_incoming_proposal("s", proposal(pid), NOW)

# Replicated create_proposal must mint the SAME pid on every process
# (deterministic content-derived ids in multi-host mode) — otherwise the
# SPMD control plane silently de-syncs.
created = engine.create_proposal(
    "create-check",
    CreateProposalRequest(
        name="replicated", payload=b"x", proposal_owner=b"o" * 20,
        expected_voters_count=3, expiration_timestamp=60,
        liveness_criteria_yes=True,
    ),
    NOW,
)
from jax.experimental import multihost_utils
agreed_pid = multihost_utils.process_allgather(
    np.array([created.proposal_id], np.int64)
)
assert int(np.min(agreed_pid)) == int(np.max(agreed_pid)), agreed_pid
engine.delete_scope("create-check")
local_pids = [pid for pid in pids if engine.is_local("s", pid)]
assert 0 < len(local_pids) < P, local_pids  # both processes own some

# Data plane: two rounds of scalar ingest, each process only its own
# sessions (collective cadence: one ingest_votes call per round each).
voters = [StubConsensusSigner(bytes([i + 1]) * 20) for i in range(2)]
ferries = {pid: engine.get_proposal("s", pid) for pid in pids}
for voter in voters:
    batch = []
    for pid in pids:
        vote = build_vote(ferries[pid], True, voter, NOW + 1)
        ferries[pid].votes.append(vote)
        if pid in local_pids:
            batch.append(("s", vote))
    statuses = engine.ingest_votes(batch, NOW + 2)
    assert (statuses == int(StatusCode.OK)).all(), statuses

# 2 YES of n=3 (quorum 2): every local session decided; events local-only.
reached = sorted(set(drain_pids("ConsensusReached")))
assert reached == sorted(local_pids), (reached, local_pids)
for pid in local_pids:
    assert engine.get_consensus_result("s", pid) is True
# Remote results lag until the next collective syncs the mirror — asserted
# globally after the sweep below.

# Misrouted vote: a vote for a remote session reports SESSION_NOT_FOUND
# on this host and the collective cadence still holds (both processes
# dispatch one batch).
remote_pid = next(pid for pid in pids if pid not in local_pids)
stray = build_vote(ferries[remote_pid], True, StubConsensusSigner(b"z" * 20), NOW + 3)
statuses = engine.ingest_votes([("s", stray)], NOW + 4)
assert statuses.tolist() == [int(StatusCode.SESSION_NOT_FOUND)], statuses

# Columnar on the fleet: one more deterministic proposal each side, fed
# through ingest_columnar with process-local rows (cadence agreed via the
# engine's allgather padding — process 1 passes an empty local batch in
# round 2 while process 0 still has rows).
cpid = 2000
engine.process_incoming_proposal("s", proposal(cpid, n=4), NOW)
c_owner = engine.is_local("s", cpid)
cvoters = [StubConsensusSigner(bytes([40 + i]) * 20) for i in range(3)]
ferry = engine.get_proposal("s", cpid)
cvotes = []
for signer in cvoters:
    vote = build_vote(ferry, True, signer, NOW + 5)
    ferry.votes.append(vote)
    cvotes.append(vote)
if c_owner:
    st = engine.ingest_columnar(
        "s",
        np.full(3, cpid, np.int64),
        np.array([engine.voter_gid(v.vote_owner) for v in cvotes]),
        np.array([v.vote for v in cvotes]),
        NOW + 6,
        wire_votes=[v.encode() for v in cvotes],
    )
    assert (st == int(StatusCode.OK)).all(), st
else:
    st = engine.ingest_columnar(
        "s", np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, bool), NOW + 6
    )
    assert len(st) == 0
columnar_events = drain_pids("ConsensusReached")
assert (cpid in columnar_events) == c_owner, (columnar_events, c_owner)
if c_owner:
    exported = engine.get_proposal("s", cpid)
    assert len(exported.votes) == 3  # retained chain materializes

# Collective single-session timeout: decided idempotently everywhere,
# event on the owner only.
tpid = 3000
engine.process_incoming_proposal("s", proposal(tpid, n=3), NOW)
result = engine.handle_consensus_timeout("s", tpid, NOW + 20_000)
assert result is True  # liveness YES fills silent voters on every process
t_events = drain_pids("ConsensusReached")
assert (tpid in t_events) == engine.is_local("s", tpid), t_events

# Collective failing timeout: n=2 unanimity undecidable; both processes
# raise, only the owner emits ConsensusFailed.
fpid = 3001
engine.process_incoming_proposal("s", proposal(fpid, n=2), NOW)
try:
    engine.handle_consensus_timeout("s", fpid, NOW + 20_000)
    raise SystemExit("expected InsufficientVotesAtTimeout")
except InsufficientVotesAtTimeout:
    pass
f_events = drain_pids("ConsensusFailed" + "Event")
assert (fpid in f_events) == engine.is_local("s", fpid), f_events

# Collective sweep: one short-expiry session, swept by both, owned results
# and events on the owner only.
spid = 4000
engine.process_incoming_proposal("s", proposal(spid, n=3, expiry=10), NOW)
swept = engine.sweep_timeouts(NOW + 100)
swept_pids = [pid for _, pid, _ in swept]
assert (spid in swept_pids) == engine.is_local("s", spid), swept

# Fleet-wide truth after the collective sweep (which synced the state
# mirror): every process sees every session's result, local or not.
for pid in pids + [cpid, tpid, spid]:
    assert engine.get_consensus_result("s", pid) is True, pid
stats = engine.get_scope_stats("s")
assert stats.total_sessions == P + 4, stats.__dict__
assert stats.consensus_reached == P + 3, stats.__dict__  # all but failed fpid
assert stats.failed_sessions == 1, stats.__dict__

# ── Multi-scope columnar + spill-heavy population + fleet checkpoint ──
# Exhaust the remaining device slots with filler sessions so the next 9
# all HOST-SPILL: replicated on every process, votes applied fleet-wide,
# events from process 0 only.
drain_pids()  # flush leftovers (e.g. the sweep's owner-side event)
fill_pids = [4500 + i for i in range(engine.pool().free_slots)]
for pid in fill_pids:
    engine.process_incoming_proposal("fill", proposal(pid, n=3), NOW)
assert engine.pool().free_slots == 0
mscopes = ["m0", "m1", "m2"]
mpids = {s: [5000 + 100 * k + j for j in range(3)] for k, s in enumerate(mscopes)}
for s in mscopes:
    for pid in mpids[s]:
        engine.process_incoming_proposal(s, proposal(pid, n=3), NOW)
        assert engine.is_local(s, pid)  # replicated spill: local everywhere

mv = [StubConsensusSigner(bytes([70 + i]) * 20) for i in range(2)]
col_sidx, col_pids, col_gids, col_vals = [], [], [], []
for k, s in enumerate(mscopes):
    for pid in mpids[s]:
        ferry = engine.get_proposal(s, pid)
        for voter in mv:
            v = build_vote(ferry, True, voter, NOW + 7)
            ferry.votes.append(v)
            col_sidx.append(k)
            col_pids.append(pid)
            col_gids.append(engine.voter_gid(v.vote_owner))
            col_vals.append(True)
st = engine.ingest_columnar_multi(
    mscopes,
    np.array(col_sidx, np.int64),
    np.array(col_pids, np.int64),
    np.array(col_gids, np.int64),
    np.array(col_vals, bool),
    NOW + 8,
)
assert (st == int(StatusCode.OK)).all(), st

# Exact events: all 9 decisions, on process 0 ONLY (spill event ownership).
m_events = sorted(drain_pids("ConsensusReached"))
m_expected = sorted(p for s in mscopes for p in mpids[s]) if process_id == 0 else []
assert m_events == m_expected, (m_events, m_expected)

# Exact per-scope histograms on EVERY process (mirror of the dryrun's
# exact-count discipline, at 2 real processes).
for s in mscopes:
    mstats = engine.get_scope_stats(s)
    assert (
        mstats.total_sessions, mstats.active_sessions,
        mstats.consensus_reached, mstats.failed_sessions,
    ) == (3, 0, 3, 0), mstats.__dict__
    for pid in mpids[s]:
        assert engine.get_consensus_result(s, pid) is True

# Fleet checkpoint: each process persists the replicated scopes, the fleet
# proves the stored state is byte-identical everywhere, and a fresh engine
# restores it with identical histograms and tallies.
import hashlib
from hashgraph_tpu import InMemoryConsensusStorage
from hashgraph_tpu.engine.session_sync import state_code_of

storage = InMemoryConsensusStorage()
for s in mscopes:
    for pid in mpids[s]:
        storage.save_session(s, engine.export_session(s, pid))
digest = hashlib.sha256()
for s in mscopes:
    for sess in sorted(
        storage.list_scope_sessions(s), key=lambda x: x.proposal.proposal_id
    ):
        digest.update(sess.proposal.encode())
        digest.update(bytes([state_code_of(sess.state)]))
        digest.update(repr(sorted(sess.tallies.items())).encode())
agreed_digest = multihost_utils.process_allgather(
    np.frombuffer(digest.digest()[:8], np.int64)
)
assert int(np.min(agreed_digest)) == int(np.max(agreed_digest)), "fleet desync"

restored = TpuConsensusEngine(
    StubConsensusSigner(b"fleet-signer-00000000"[:20]),
    capacity=16, voter_capacity=8, max_sessions_per_scope=64,
)
n_loaded = restored.load_from_storage(storage)
assert n_loaded == 9, n_loaded
for s in mscopes:
    rstats = restored.get_scope_stats(s)
    assert (
        rstats.total_sessions, rstats.active_sessions,
        rstats.consensus_reached, rstats.failed_sessions,
    ) == (3, 0, 3, 0), rstats.__dict__
    for pid in mpids[s]:
        assert restored.get_consensus_result(s, pid) is True
        assert len(restored.export_session(s, pid).tallies) == 2

owned = sorted(local_pids + [p for p in (cpid, tpid, fpid, spid) if engine.is_local("s", p)])
print(f"ENGINE_MULTIHOST_OK p{process_id} owned={owned}")
"""


def _run_two_process(tmp_path, script, marker):
    worker = tmp_path / "worker.py"
    worker.write_text(script)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), coordinator],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=220)
        outs.append(out)
    for i, (proc, out) in enumerate(zip(procs, outs)):
        if proc.returncode != 0 and _CPU_COLLECTIVES_UNIMPLEMENTED in out:
            # The installed jaxlib's CPU backend has no multi-process
            # collective implementation (sharded computations across
            # jax.distributed processes raise INVALID_ARGUMENT at
            # dispatch). The contract these tests pin down is exercised
            # for real on TPU pods / newer CPU backends; a red run here
            # would only re-report the backend gap (CHANGES.md PR 6).
            pytest.skip(
                "multi-process CPU collectives not implemented by this "
                "jaxlib backend (XlaRuntimeError: 'Multiprocess "
                "computations aren't implemented on the CPU backend')"
            )
        assert proc.returncode == 0, f"process {i} failed:\n{out}"
        assert f"{marker} p{i}" in out, out
    return outs


# The exact backend-gap signature: anything else (an assertion failure in
# the worker, a crash, a timeout) must still FAIL the test. ONE home:
# parallel/multihost.py owns the string because the runtime capability
# probe (collectives_available) discriminates on the same signature —
# what used to be a test-only skip-guard is now the production
# psum-vs-fabric tally path selector.
from hashgraph_tpu.parallel.multihost import (  # noqa: E402
    COLLECTIVES_GAP_SIGNATURE as _CPU_COLLECTIVES_UNIMPLEMENTED,
)


def test_collectives_probe_single_process():
    """On a single-process backend the probe is trivially True (every
    collective is an in-process reduction) and memoizes."""
    from hashgraph_tpu.parallel.multihost import collectives_available

    assert collectives_available(refresh=True) is True
    assert collectives_available() is True  # memoized path


def test_collectives_gap_signature_matcher():
    """The discriminator accepts exceptions and strings, matches only
    the known backend-gap signature, and never a generic failure."""
    from hashgraph_tpu.parallel import multihost as mh

    wrapped = RuntimeError(
        "INVALID_ARGUMENT: " + mh.COLLECTIVES_GAP_SIGNATURE + " (dispatch)"
    )
    assert mh.is_collectives_gap(wrapped)
    assert mh.is_collectives_gap(mh.COLLECTIVES_GAP_SIGNATURE)
    assert not mh.is_collectives_gap(RuntimeError("connection refused"))
    assert not mh.is_collectives_gap(ValueError("shape mismatch"))


def test_collectives_probe_drives_federation_tally_path():
    """The federation's tally-path selector consults the probe: on this
    single-process CPU backend there is no cross-process jax fleet, so
    cross-host tallies must ride the gossip fabric's OP_FLEET_TALLY
    frames, not psum."""
    import jax

    from hashgraph_tpu.parallel.federation import tally_path

    assert jax.process_count() == 1
    assert tally_path() == "fabric"


def test_two_process_engine_on_multihost_pool(tmp_path):
    """The FULL engine surface on a MultiHostPool from 2 processes: SPMD
    control plane, local-only ingest (scalar + columnar), owner-only event
    emission — the 'never double-publishes' claim as passing assertions."""
    outs = _run_two_process(tmp_path, _ENGINE_WORKER, "ENGINE_MULTIHOST_OK")
    # Cross-process: ownership must partition the sessions — no pid owned
    # (and therefore no event emitted) by both processes.
    import re

    owned = []
    for out in outs:
        match = re.search(r"owned=\[([0-9, ]*)\]", out)
        assert match, out
        owned.append({int(x) for x in match.group(1).split(",") if x.strip()})
    assert owned[0] & owned[1] == set(), owned
    assert len(owned[0]) > 0 and len(owned[1]) > 0


def test_two_process_multihost_pool(tmp_path):
    _run_two_process(tmp_path, _WORKER, "MULTIHOST_OK")


def test_canonical_scope_bytes_rejects_default_repr():
    """Deterministic multi-host pids hash the scope; a default object repr
    embeds a memory address and would silently de-sync the replicated
    control plane, so non-canonical scope types must be a hard error."""
    from hashgraph_tpu.engine.engine import _canonical_scope_bytes

    assert _canonical_scope_bytes("s") == b"s:s"
    assert _canonical_scope_bytes(b"s") == b"b:s"
    assert _canonical_scope_bytes(7) == b"i:7"

    class Opaque:
        pass

    with pytest.raises(TypeError, match="canonical"):
        _canonical_scope_bytes(Opaque())
