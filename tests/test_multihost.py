"""2-process jax.distributed integration: the MultiHostPool end-to-end.

Spawns two real Python processes, each contributing 2 virtual CPU devices
to one 4-device mesh, and drives the full multi-host contract: replicated
control plane (allocate/timeout), process-local vote ingest with agreed
grid shapes, psum global stats, and owner-only transition reporting. This
is the distributed-communication-backend check from SURVEY §2.3 — DCN-free
vote routing with consensus state sharded across hosts."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

process_id = int(sys.argv[1])
coordinator = sys.argv[2]

jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=process_id
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())
assert len(jax.local_devices()) == 2

sys.path.insert(0, os.getcwd())  # spawned with cwd = repo root
from hashgraph_tpu.ops.decide import (
    STATE_ACTIVE,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    required_votes_np,
)
from hashgraph_tpu.parallel import MultiHostPool, distributed_consensus_mesh

NOW = 1_700_000_000
mesh = distributed_consensus_mesh()
pool = MultiHostPool(capacity_per_device=4, voter_capacity=8, mesh=mesh)
assert pool.capacity == 16
lo, hi = pool.local_slots()
assert (lo, hi) == ((0, 8) if process_id == 0 else (8, 16)), (lo, hi)

# Control plane: REPLICATED — identical allocation on both processes.
# 8 proposals, round-robin across the 4 devices: slots 0,4,8,12,1,5,9,13.
P = 8
slots = pool.allocate_batch(
    keys=[("s", i) for i in range(P)],
    n=np.full(P, 3),
    req=required_votes_np(np.full(P, 3), 2.0 / 3.0),
    cap=np.full(P, 2),
    gossip=np.ones(P, bool),
    liveness=np.full(P, True),
    expiry=np.array([NOW + (10_000 if i % 2 == 0 else 10) for i in range(P)]),
    created_at=np.full(P, NOW),
)
assert slots == [0, 4, 8, 12, 1, 5, 9, 13], slots

# Data plane: each process ingests votes ONLY for its own slots; cadence
# is collective (both processes dispatch twice).
mine = [s for s in slots if lo <= s < hi]
assert len(mine) == 4
statuses_seen = []
for lane in range(2):
    batch_slots = np.array(mine, np.int64)
    lanes = np.full(4, lane, np.int32)
    values = np.ones(4, bool)  # 2 YES of n=3 -> quorum 2 -> REACHED_YES
    pending = pool.ingest_async(batch_slots, lanes, values, NOW)
    statuses, transitions = pool.complete(pending)
    statuses_seen.append(list(statuses))
assert statuses_seen[0] == [0, 0, 0, 0], statuses_seen
assert statuses_seen[1] == [0, 0, 0, 0], statuses_seen
# Second lane decided every local session; transitions are local-only.
assert {s for s, _ in transitions} == set(mine)
assert all(st == STATE_REACHED_YES for _, st in transitions)

# Global stats via psum: every process sees the fleet-wide histogram.
counts = pool.global_state_counts()
assert counts[STATE_REACHED_YES] == 8, counts
assert counts[STATE_ACTIVE] == 0, counts

# Empty collective dispatch: process 1 has nothing this round but still
# participates (process 0 votes NO on nothing — both empty keeps it easy).
pending = pool.ingest_async(np.empty(0, np.int64), np.empty(0, np.int32), np.empty(0, bool), NOW)
st, tr = pool.complete(pending)
assert len(st) == 0 and tr == []

# Timeout sweep: REPLICATED args; each process gets back only its slots.
swept = pool.timeout(slots)
assert {s for s, _ in swept} == set(mine), swept
assert all(st == STATE_REACHED_YES for _, st in swept)  # idempotent: stays decided

print(f"MULTIHOST_OK p{process_id} slots={mine}")
"""


def test_two_process_multihost_pool(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), coordinator],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=220)
        outs.append(out)
    for i, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"process {i} failed:\n{out}"
        assert f"MULTIHOST_OK p{i}" in out, out
