"""Tracing: counters, spans, export, and engine instrumentation."""

import json

from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.tracing import Tracer
from hashgraph_tpu import CreateProposalRequest, build_vote

from common import NOW, random_stub_signer


class TestTracer:
    def test_disabled_is_noop(self):
        t = Tracer()
        with t.span("x"):
            pass
        t.count("c", 5)
        t.event("e")
        assert t.counters() == {}
        assert t.spans() == []

    def test_spans_and_counters(self):
        t = Tracer(enabled=True)
        with t.span("work", size=3):
            t.count("items", 3)
        stats = t.span_stats("work")
        assert stats["count"] == 1
        assert stats["total"] > 0
        assert t.counters()["items"] == 3
        assert t.counters()["span.work.calls"] == 1

    def test_export_jsonl(self, tmp_path):
        t = Tracer(enabled=True)
        with t.span("a"):
            pass
        t.event("boom", detail="x")
        path = tmp_path / "trace.jsonl"
        t.export_jsonl(str(path))
        lines = [json.loads(line) for line in path.read_text().splitlines()]
        kinds = {line["type"] for line in lines}
        assert kinds == {"counters", "span", "event"}

    def test_engine_instrumentation(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        engine.tracer = Tracer(enabled=True)
        pid = engine.create_proposal(
            "s",
            CreateProposalRequest("p", b"", b"o", 3, 100, True),
            NOW,
        ).proposal_id
        v1 = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        v2 = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        engine.ingest_votes([("s", v1)], NOW)
        engine.ingest_votes([("s", v2)], NOW)
        engine.sweep_timeouts(NOW + 200)
        counters = engine.tracer.counters()
        assert counters["engine.votes_in"] == 2
        assert counters["engine.votes_accepted"] == 2
        assert counters["engine.transitions"] == 1  # second vote decided
        assert counters["engine.timeout_sweeps"] == 1
        assert counters["span.engine.device_ingest.calls"] == 2
