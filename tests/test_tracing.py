"""Tracing: counters, spans, export, and engine instrumentation."""

import json
import threading

import pytest

from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.tracing import Tracer
from hashgraph_tpu import CreateProposalRequest, build_vote

from common import NOW, random_stub_signer


class _PoisonLock:
    """Lock stand-in that fails the test if the hot path ever acquires it
    — the disabled tracer's span/count/event must be one attribute check."""

    def __enter__(self):
        raise AssertionError("disabled tracer touched its lock")

    def __exit__(self, *exc):
        raise AssertionError("disabled tracer touched its lock")

    def acquire(self, *args, **kwargs):
        raise AssertionError("disabled tracer touched its lock")

    def release(self):
        raise AssertionError("disabled tracer touched its lock")


class TestTracer:
    def test_disabled_is_noop(self):
        t = Tracer()
        with t.span("x"):
            pass
        t.count("c", 5)
        t.event("e")
        assert t.counters() == {}
        assert t.spans() == []

    def test_spans_and_counters(self):
        t = Tracer(enabled=True)
        with t.span("work", size=3):
            t.count("items", 3)
        stats = t.span_stats("work")
        assert stats["count"] == 1
        assert stats["total"] > 0
        assert t.counters()["items"] == 3
        assert t.counters()["span.work.calls"] == 1

    def test_disabled_overhead_no_lock(self):
        """Disabled-tracer smoke test: span/count/event must never reach
        the lock (one ``enabled`` attribute check and out)."""
        t = Tracer()
        t._lock = _PoisonLock()
        for _ in range(1_000):
            with t.span("hot"):
                pass
            t.count("c")
            t.event("e")

    def test_span_drop_counter_past_cap(self):
        t = Tracer(enabled=True, max_records=2)
        for _ in range(5):
            with t.span("work"):
                pass
        assert len(t.spans("work")) == 2  # capped
        counters = t.counters()
        assert counters["span.dropped"] == 3
        assert counters["span.work.calls"] == 5  # totals stay exact

    def test_concurrent_counters_and_spans(self):
        t = Tracer(enabled=True)

        def hammer():
            for _ in range(2_000):
                t.count("c")
                with t.span("s"):
                    pass

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        counters = t.counters()
        assert counters["c"] == 16_000
        assert counters["span.s.calls"] == 16_000

    def test_export_jsonl_atomic_on_failure(self, tmp_path):
        """A failing export (unserializable event attr) must leave the
        previous trace file byte-identical and no temp litter behind."""
        path = tmp_path / "trace.jsonl"
        t = Tracer(enabled=True)
        t.count("good", 1)
        t.export_jsonl(str(path))
        original = path.read_bytes()
        t.event("bad", payload=object())  # json.dumps will raise
        with pytest.raises(TypeError):
            t.export_jsonl(str(path))
        assert path.read_bytes() == original
        assert list(tmp_path.iterdir()) == [path]  # no stray temp files

    def test_export_jsonl(self, tmp_path):
        t = Tracer(enabled=True)
        with t.span("a"):
            pass
        t.event("boom", detail="x")
        path = tmp_path / "trace.jsonl"
        t.export_jsonl(str(path))
        lines = [json.loads(line) for line in path.read_text().splitlines()]
        kinds = {line["type"] for line in lines}
        assert kinds == {"counters", "span", "event"}

    def test_engine_instrumentation(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        engine.tracer = Tracer(enabled=True)
        pid = engine.create_proposal(
            "s",
            CreateProposalRequest("p", b"", b"o", 3, 100, True),
            NOW,
        ).proposal_id
        v1 = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        v2 = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        engine.ingest_votes([("s", v1)], NOW)
        engine.ingest_votes([("s", v2)], NOW)
        engine.sweep_timeouts(NOW + 200)
        counters = engine.tracer.counters()
        assert counters["engine.votes_in"] == 2
        assert counters["engine.votes_accepted"] == 2
        assert counters["engine.transitions"] == 1  # second vote decided
        assert counters["engine.timeout_sweeps"] == 1
        assert counters["span.engine.device_ingest.calls"] == 2
