"""Pipelined validated ingest vs the sequential flow.

The double-buffered path (`ingest_votes_pipelined`, and the async
verify prepass it is built on) must change WHERE the crypto runs, never
a verdict: for any batch sequence it must report identical statuses,
leave identical stored chains, and (through DurableEngine) replay to the
identical state after a crash. With the native pool absent the deferred
sync fallback must restore today's behavior byte for byte — the stub
scheme exercises exactly that path.
"""

import numpy as np
import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    Ed25519ConsensusSigner,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine

from common import NOW

N_SIGNERS = 5
SIGNERS = [StubConsensusSigner(bytes([i + 1]) * 20) for i in range(N_SIGNERS)]


def _fresh_engine(signer=None, cache="default"):
    return TpuConsensusEngine(
        signer if signer is not None else StubConsensusSigner(b"\x42" * 20),
        capacity=32,
        voter_capacity=8,
        verify_cache=cache,
    )


def _req(voters=N_SIGNERS * 2):
    return CreateProposalRequest(
        name="p",
        payload=b"x",
        proposal_owner=b"o",
        expected_voters_count=voters,
        expiration_timestamp=10_000,
        liveness_criteria_yes=True,
    )


def _make_batches(engine, scope, n_props, corrupt=(), unknown=()):
    """Per-proposal single votes sliced into batches of 7, with optional
    corrupted signatures and votes for unknown sessions mixed in.
    Returns (batches, creation-ordered proposal ids)."""
    proposals = [
        engine.create_proposal(scope, _req(), NOW) for _ in range(n_props)
    ]
    items = []
    for i, proposal in enumerate(proposals):
        for j, signer in enumerate(SIGNERS):
            vote = build_vote(proposal, bool(j % 2), signer, NOW + 1 + j)
            if (i, j) in corrupt:
                vote.signature = bytes([vote.signature[0] ^ 1]) + vote.signature[1:]
            if (i, j) in unknown:
                vote.proposal_id = 999_000 + i
            items.append((scope, vote))
    return (
        [items[k : k + 7] for k in range(0, len(items), 7)],
        [p.proposal_id for p in proposals],
    )


def _state_fingerprint(engine, scope, pids):
    """Per-proposal session state keyed by CREATION ORDER (proposal and
    vote ids are random per engine, so a cross-engine comparison must
    key on the deterministic fields only)."""
    out = []
    for ordinal, pid in enumerate(pids):
        slot = engine._index.get((scope, pid))
        if slot is None:
            out.append((ordinal, None, None))
            continue
        record = engine._records[slot]
        out.append(
            (
                ordinal,
                tuple(
                    (v.vote_owner, v.vote, v.timestamp)
                    for v in record.proposal.votes
                ),
                sorted(record.votes),
            )
        )
    return out


class TestPipelinedEquivalence:
    @pytest.mark.parametrize("cache", ["default", None])
    def test_statuses_and_chains_identical(self, cache):
        corrupt = {(0, 1), (2, 3)}
        unknown = {(1, 0)}
        seq = _fresh_engine(cache=cache)
        pip = _fresh_engine(cache=cache)
        seq_batches, seq_pids = _make_batches(seq, "s", 3, corrupt, unknown)
        pip_batches, pip_pids = _make_batches(pip, "s", 3, corrupt, unknown)
        seq_out = [seq.ingest_votes(b, NOW) for b in seq_batches]
        pip_out = pip.ingest_votes_pipelined(pip_batches, NOW)
        assert len(seq_out) == len(pip_out)
        for a, b in zip(seq_out, pip_out):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert _state_fingerprint(seq, "s", seq_pids) == _state_fingerprint(
            pip, "s", pip_pids
        )

    def test_empty_and_single_batches(self):
        engine = _fresh_engine()
        assert engine.ingest_votes_pipelined([], NOW) == []
        batches, _ = _make_batches(engine, "s", 1)
        out = engine.ingest_votes_pipelined([batches[0]], NOW)
        assert len(out) == 1 and int(np.asarray(out[0])[0]) == 0

    def test_pre_validated_skips_prepass(self):
        engine = _fresh_engine()
        batches, _ = _make_batches(engine, "s", 2)
        out = engine.ingest_votes_pipelined(batches, NOW, pre_validated=True)
        flat = np.concatenate([np.asarray(o) for o in out])
        assert int(np.sum(flat == 0)) == len(flat)

    def test_native_scheme_pipelined(self):
        """Ed25519 batches through the real pool (when available; the
        deferred-sync fallback covers the rest) match sequential."""
        signers = [Ed25519ConsensusSigner.random() for _ in range(3)]
        seq = _fresh_engine(Ed25519ConsensusSigner.random())
        pip = _fresh_engine(Ed25519ConsensusSigner.random())
        outs = []
        for engine in (seq, pip):
            proposals = [
                engine.create_proposal("s", _req(), NOW) for _ in range(2)
            ]
            items = []
            for i, proposal in enumerate(proposals):
                for j, signer in enumerate(signers):
                    vote = build_vote(proposal, True, signer, NOW + 1 + j)
                    if (i, j) == (1, 1):
                        vote.signature = b"\x00" * 64
                    items.append(("s", vote))
            batches = [items[k : k + 3] for k in range(0, len(items), 3)]
            if engine is seq:
                outs.append([engine.ingest_votes(b, NOW) for b in batches])
            else:
                outs.append(engine.ingest_votes_pipelined(batches, NOW))
        for a, b in zip(outs[0], outs[1]):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestVerifyVotesAsync:
    def test_public_prepass_matches_validate(self):
        engine = _fresh_engine()
        proposal = engine.create_proposal("s", _req(), NOW)
        good = build_vote(proposal, True, SIGNERS[0], NOW + 1)
        bad = build_vote(proposal, True, SIGNERS[1], NOW + 1)
        bad.signature = b"\x00" * 32
        pend = engine.verify_votes_async([good, bad])
        verdicts, hashes = pend.collect()
        assert verdicts[0] is True
        assert verdicts[1] is not True
        assert hashes[0] == good.vote_hash
        # Idempotent collect.
        assert pend.collect() == (verdicts, hashes)


class TestDurablePipelinedReplay:
    def test_wal_replay_parity(self, tmp_path):
        """Crash-replay after a pipelined ingest reconstructs the same
        sessions a sequential ingest (live or replayed) produces."""
        from hashgraph_tpu.wal import DurableEngine, replay

        def build(dir_name, pipelined):
            durable = DurableEngine(
                _fresh_engine(), str(tmp_path / dir_name),
                fsync_policy="off",
            )
            proposals = [
                durable.create_proposal("s", _req(), NOW) for _ in range(2)
            ]
            items = []
            for proposal in proposals:
                for j, signer in enumerate(SIGNERS):
                    items.append(
                        ("s", build_vote(proposal, bool(j % 2), signer, NOW + 1 + j))
                    )
            batches = [items[k : k + 4] for k in range(0, len(items), 4)]
            if pipelined:
                durable.ingest_votes_pipelined(batches, NOW)
            else:
                for b in batches:
                    durable.ingest_votes(b, NOW)
            return durable, [p.proposal_id for p in proposals]

        a, a_pids = build("pipelined", True)
        b, b_pids = build("sequential", False)
        assert _state_fingerprint(a.engine, "s", a_pids) == _state_fingerprint(
            b.engine, "s", b_pids
        )
        a.close()
        # Crash-replay the pipelined WAL into a fresh engine (replay
        # preserves proposal ids, so a's pid list applies).
        recovered = _fresh_engine()
        stats = replay(str(tmp_path / "pipelined"), recovered)
        assert stats.errors == []
        assert _state_fingerprint(recovered, "s", a_pids) == _state_fingerprint(
            b.engine, "s", b_pids
        )
        b.close()
