"""Engine thread-safety: parallel voters, same-voter races, mixed ops.

Mirrors the reference's concurrency suite (tests/concurrency_tests.rs) on
the TPU engine: N threads hammer the same engine; outcomes must equal the
sequential semantics (exactly one success per race, consistent final state).
"""

import threading

import pytest

from hashgraph_tpu import (
    ConsensusError,
    CreateProposalRequest,
    DuplicateVote,
    StatusCode,
    UserAlreadyVoted,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine

from common import NOW, random_stub_signer


def request(n, name="p", exp=1000):
    return CreateProposalRequest(
        name=name,
        payload=b"",
        proposal_owner=b"o",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=True,
    )


class TestEngineConcurrency:
    def test_parallel_distinct_voters_all_succeed(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=16
        )
        # Threshold 1.0 so all 10 votes land before a decision cuts them off.
        engine.scope("s").with_threshold(1.0).initialize()
        pid = engine.create_proposal("s", request(10), NOW).proposal_id
        base = engine.get_proposal("s", pid)
        votes = [
            build_vote(base, True, random_stub_signer(), NOW) for _ in range(10)
        ]
        barrier = threading.Barrier(10)
        results = []
        lock = threading.Lock()

        def worker(vote):
            barrier.wait()
            st = engine.ingest_votes([("s", vote)], NOW, pre_validated=True)
            with lock:
                results.append(int(st[0]))

        threads = [threading.Thread(target=worker, args=(v,)) for v in votes]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results.count(int(StatusCode.OK)) == 10
        assert engine.export_session("s", pid).proposal.round == 2

    def test_same_voter_race_single_success(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=16
        )
        pid = engine.create_proposal("s", request(10), NOW).proposal_id
        voter = random_stub_signer()
        base = engine.get_proposal("s", pid)
        vote = build_vote(base, True, voter, NOW)
        barrier = threading.Barrier(5)
        outcomes = []
        lock = threading.Lock()

        def worker():
            barrier.wait()
            st = engine.ingest_votes([("s", vote.clone())], NOW, pre_validated=True)
            with lock:
                outcomes.append(int(st[0]))

        threads = [threading.Thread(target=worker) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outcomes.count(int(StatusCode.OK)) == 1
        assert outcomes.count(int(StatusCode.DUPLICATE_VOTE)) == 4

    def test_scorecard_accounting_under_concurrent_batches(self):
        """8 threads each ingest a distinct batch of validated votes;
        the health scorecards must account every admission exactly once
        (no lost updates across the monitor's lock) and grade everyone
        healthy."""
        from hashgraph_tpu.obs import MetricsRegistry
        from hashgraph_tpu.obs.health import GRADE_HEALTHY, HealthMonitor

        monitor = HealthMonitor(registry=MetricsRegistry())
        engine = TpuConsensusEngine(
            random_stub_signer(),
            capacity=16,
            voter_capacity=64,
            health_monitor=monitor,
        )
        engine.scope("s").with_threshold(1.0).initialize()
        pid = engine.create_proposal("s", request(64), NOW).proposal_id
        base = engine.get_proposal("s", pid)
        signers = [random_stub_signer() for _ in range(32)]
        votes = [build_vote(base, True, s, NOW) for s in signers]
        batches = [votes[i::8] for i in range(8)]
        barrier = threading.Barrier(8)
        counts = []
        lock = threading.Lock()

        def worker(batch):
            barrier.wait()
            st = engine.ingest_votes(
                [("s", v) for v in batch], NOW, pre_validated=True
            )
            with lock:
                counts.append(sum(int(c) == int(StatusCode.OK) for c in st))

        threads = [threading.Thread(target=worker, args=(b,)) for b in batches]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(counts) == 32
        cards = [monitor.scorecard(s.identity()) for s in signers]
        assert all(c is not None and c["votes_admitted"] == 1 for c in cards)
        assert {c["grade"] for c in cards} == {GRADE_HEALTHY}
        assert monitor.evidence_count() == 0

    def test_parallel_proposal_creation(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=64, voter_capacity=8,
            max_sessions_per_scope=64,
        )
        barrier = threading.Barrier(8)
        pids = []
        lock = threading.Lock()

        def worker(i):
            barrier.wait()
            p = engine.create_proposal(f"scope{i % 2}", request(3, f"p{i}"), NOW + i)
            with lock:
                pids.append(p.proposal_id)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(pids)) == 8
        total = (
            engine.get_scope_stats("scope0").total_sessions
            + engine.get_scope_stats("scope1").total_sessions
        )
        assert total == 8
