"""Sharded pool on the virtual 8-device CPU mesh.

conftest.py forces ``--xla_force_host_platform_device_count=8`` before jax
initializes, so these tests exercise real multi-device sharding + shard_map
routing without TPU hardware. The bar is the same as for the single-device
engine: observable behavior identical to the scalar service.
"""

import numpy as np
import pytest

import jax

from hashgraph_tpu import (
    ConsensusError,
    CreateProposalRequest,
    NetworkType,
    SessionNotFound,
    StatusCode,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.ops import STATE_ACTIVE, STATE_FREE, STATE_REACHED_YES
from hashgraph_tpu.parallel import ShardedPool, consensus_mesh

from common import NOW, make_service, random_stub_signer


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    return consensus_mesh(8)


def make_sharded_engine(mesh, per_device=8, voter_capacity=16, **kw):
    pool = ShardedPool(per_device, voter_capacity, mesh)
    return TpuConsensusEngine(random_stub_signer(), pool=pool, **kw)


def request(n=3, name="prop", exp=1000, liveness=True):
    return CreateProposalRequest(
        name=name,
        payload=b"payload",
        proposal_owner=b"owner",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


class TestShardedPoolLayout:
    def test_arrays_are_sharded(self, mesh):
        pool = ShardedPool(8, 16, mesh)
        assert pool.capacity == 64
        sharding = pool._state.sharding
        assert sharding.num_devices == 8
        # [P, V] arrays shard on the slot axis only.
        assert pool._vote_mask.sharding.spec[0] == "p"

    def test_round_robin_allocation(self, mesh):
        pool = ShardedPool(4, 8, mesh)
        slots = pool.allocate_batch(
            keys=[("s", i) for i in range(8)],
            n=np.full(8, 3),
            req=np.full(8, 2),
            cap=np.full(8, 2),
            gossip=np.ones(8, bool),
            liveness=np.ones(8, bool),
            expiry=np.full(8, NOW + 100),
            created_at=np.full(8, NOW),
        )
        owners = {s // pool.local_capacity for s in slots}
        assert owners == set(range(8))  # one slot per device first

    def test_global_state_counts_psum(self, mesh):
        pool = ShardedPool(4, 8, mesh)
        pool.allocate_batch(
            keys=[("s", i) for i in range(5)],
            n=np.full(5, 3),
            req=np.full(5, 2),
            cap=np.full(5, 2),
            gossip=np.ones(5, bool),
            liveness=np.ones(5, bool),
            expiry=np.full(5, NOW + 100),
            created_at=np.full(5, NOW),
        )
        counts = pool.global_state_counts()
        assert counts[STATE_ACTIVE] == 5
        assert counts[STATE_FREE] == 32 - 5
        # Device-side psum agrees with the host mirror.
        assert counts == {**{k: 0 for k in counts}, **pool.state_counts()}


class TestShardedEngine:
    def test_quickstart_on_mesh(self, mesh):
        engine = make_sharded_engine(mesh)
        pid = engine.create_proposal("s", request(3), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        v = build_vote(engine.get_proposal("s", pid), True, random_stub_signer(), NOW)
        engine.process_incoming_vote("s", v, NOW)
        assert engine.get_consensus_result("s", pid) is True

    def test_cross_device_batch_ingest(self, mesh):
        """One batch touching sessions on all 8 devices."""
        engine = make_sharded_engine(mesh, per_device=4)
        pids = [
            engine.create_proposal(f"scope{i}", request(3, name=f"p{i}"), NOW).proposal_id
            for i in range(8)
        ]
        items = []
        for i, pid in enumerate(pids):
            scope = f"scope{i}"
            for _ in range(2):
                vote = build_vote(
                    engine.get_proposal(scope, pid), True, random_stub_signer(), NOW
                )
                # apply immediately to keep chains valid
                st = engine.ingest_votes([(scope, vote)], NOW)
                assert st[0] in (int(StatusCode.OK), int(StatusCode.ALREADY_REACHED))
        for i, pid in enumerate(pids):
            assert engine.get_consensus_result(f"scope{i}", pid) is True

    def test_columnar_fresh_dispatch_on_mesh(self, mesh):
        """The closed-form (scan-free) kernel also serves the sharded pool:
        one columnar batch over fresh sessions spanning all 8 devices takes
        the fresh dispatch (tracer-asserted) and decides every session."""
        from hashgraph_tpu.tracing import Tracer

        engine = make_sharded_engine(
            mesh, per_device=4, max_sessions_per_scope=32
        )
        engine.tracer = Tracer(enabled=True)
        # n=4 (quorum 3): exactly 3 YES decide on the 3rd vote, all OK.
        proposals = engine.create_proposals("s", [request(4)] * 16, NOW)
        gids = np.array(
            [engine.voter_gid(bytes([i]) * 4) for i in range(1, 4)], np.int64
        )
        pids = np.repeat(
            np.array([p.proposal_id for p in proposals], np.int64), 3
        )
        statuses = engine.ingest_columnar(
            "s", pids, np.tile(gids, 16), np.ones(48, bool), NOW + 1
        )
        assert (statuses == int(StatusCode.OK)).all(), statuses
        assert engine.tracer.counters().get("engine.fresh_dispatches") == 1
        for p in proposals:
            assert engine.get_consensus_result("s", p.proposal_id) is True

    def test_sharded_timeout_sweep(self, mesh):
        engine = make_sharded_engine(mesh, per_device=4)
        pids = [
            engine.create_proposal("s", request(5, name=f"p{i}", exp=50), NOW + i).proposal_id
            for i in range(8)
        ]
        for pid in pids[:4]:
            engine.cast_vote("s", pid, True, NOW + 10)
        swept = engine.sweep_timeouts(NOW + 100)
        assert len(swept) == 8
        # liveness=True fills every silent peer as YES at timeout, so all
        # sessions (voted or not) decide YES — same as the scalar oracle.
        assert all(result is True for _, _, result in swept)
        assert {pid for _, pid, _ in swept} == set(pids)

    @pytest.mark.parametrize("seed", range(2))
    def test_random_trace_parity_on_mesh(self, seed, mesh):
        """Randomized side-by-side trace: sharded engine vs scalar service."""
        rng = np.random.default_rng(seed)
        service = make_service()
        engine = TpuConsensusEngine(
            service.signer(),
            pool=ShardedPool(8, 16, mesh),
        )
        service_rx = service.event_bus().subscribe()
        engine_rx = engine.event_bus().subscribe()
        voters = [random_stub_signer() for _ in range(8)]
        scopes = ["alpha", "beta", "gamma"]
        for scope in scopes:
            if rng.random() < 0.5:
                service.scope(scope).with_network_type(NetworkType.P2P).initialize()
                engine.scope(scope).with_network_type(NetworkType.P2P).initialize()

        pids: list[tuple[str, int]] = []
        for step in range(50):
            now = NOW + step
            action = rng.random()
            if action < 0.25 or not pids:
                scope = scopes[int(rng.integers(len(scopes)))]
                req_obj = CreateProposalRequest(
                    name=f"p{step}",
                    payload=b"x",
                    proposal_owner=b"o",
                    expected_voters_count=int(rng.integers(2, 8)),
                    expiration_timestamp=int(rng.choice([30, 1000])),
                    liveness_criteria_yes=bool(rng.random() < 0.5),
                )
                proposal = req_obj.into_proposal(now)
                s_exc = e_exc = None
                try:
                    service.process_incoming_proposal(scope, proposal.clone(), now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                try:
                    engine.process_incoming_proposal(scope, proposal.clone(), now)
                except ConsensusError as exc:
                    e_exc = type(exc)
                assert s_exc == e_exc
                if s_exc is None:
                    pids.append((scope, proposal.proposal_id))
            elif action < 0.85:
                scope, pid = pids[int(rng.integers(len(pids)))]
                signer = voters[int(rng.integers(len(voters)))]
                choice = bool(rng.random() < 0.6)
                s_exc = e_exc = None
                vote = None
                try:
                    base = service.storage().get_proposal(scope, pid)
                    vote = build_vote(base, choice, signer, now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                if vote is not None:
                    try:
                        service.process_incoming_vote(scope, vote.clone(), now)
                    except ConsensusError as exc:
                        s_exc = type(exc)
                    try:
                        engine.process_incoming_vote(scope, vote.clone(), now)
                    except ConsensusError as exc:
                        e_exc = type(exc)
                    assert s_exc == e_exc, f"step {step}: {s_exc} vs {e_exc}"
            else:
                scope, pid = pids[int(rng.integers(len(pids)))]
                s_res = e_res = s_exc = e_exc = None
                try:
                    s_res = service.handle_consensus_timeout(scope, pid, now)
                except ConsensusError as exc:
                    s_exc = type(exc)
                try:
                    e_res = engine.handle_consensus_timeout(scope, pid, now)
                except ConsensusError as exc:
                    e_exc = type(exc)
                assert (s_res, s_exc) == (e_res, e_exc)

        for scope, pid in pids:
            s_session = service.storage().get_session(scope, pid)
            if s_session is None:
                with pytest.raises(SessionNotFound):
                    engine.get_proposal(scope, pid)
                continue
            e_session = engine.export_session(scope, pid)
            assert e_session.state == s_session.state, f"{scope}/{pid}"
            assert set(e_session.votes) == set(s_session.votes)

        # Event streams must match exactly.
        def drain(rx):
            out = []
            while (item := rx.try_recv()) is not None:
                out.append(item)
            return out

        assert drain(service_rx) == drain(engine_rx)
