"""Two-level (host, shard) placement invariants.

The federation's routing contract: every participant that constructs a
:class:`~hashgraph_tpu.parallel.federation.FederationPlacement` from the
same membership history computes IDENTICAL assignments (golden values +
a fresh-subprocess check), membership changes remap minimally (the
rendezvous invariant at the host level), live scopes are pinned and
never split, and a migration flips a shard's home atomically — no
reader ever observes dual ownership."""

import subprocess
import sys
import threading

import pytest

from hashgraph_tpu.parallel.federation import FederationPlacement

HOSTS = ["alpha", "beta", "gamma"]


def uniform():
    return FederationPlacement.uniform(HOSTS, 2)


# Pinned (host, shard) assignments: placement is a pure function of the
# membership history, so these values must never drift — a silent hash
# change would strand every live deployment's scopes.
GOLDEN = {
    "scope-0": ("gamma", "gamma:0"),
    "scope-1": ("alpha", "alpha:0"),
    "scope-2": ("gamma", "gamma:1"),
    "scope-3": ("alpha", "alpha:1"),
    "scope-4": ("alpha", "alpha:0"),
    "scope-5": ("alpha", "alpha:1"),
    "scope-6": ("alpha", "alpha:0"),
    "scope-7": ("gamma", "gamma:0"),
    "scope-8": ("alpha", "alpha:1"),
    "scope-9": ("beta", "beta:0"),
    "scope-10": ("alpha", "alpha:1"),
    "scope-11": ("beta", "beta:1"),
}


def test_golden_assignments():
    placement = uniform()
    got = {scope: placement.owner(scope) for scope in GOLDEN}
    assert got == GOLDEN


def test_fresh_subprocess_restart_stability():
    """A restarted (or different-machine) participant reconstructs the
    identical placement — no dependence on interpreter state or
    randomized hashing."""
    script = (
        "from hashgraph_tpu.parallel.federation import FederationPlacement\n"
        f"p = FederationPlacement.uniform({HOSTS!r}, 2)\n"
        "print(';'.join('%s=%s,%s' % (s, *p.owner(s))"
        " for s in ['scope-%d' % i for i in range(12)]))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, check=True,
    ).stdout.strip()
    got = {}
    for item in out.split(";"):
        scope, owner = item.split("=")
        host, shard = owner.split(",")
        got[scope] = (host, shard)
    assert got == GOLDEN


def test_second_level_matches_fleet_rendezvous():
    """The placement's shard choice and a host fleet's own rendezvous
    over the same shard set MUST coincide — both sides pin a scope at
    its first mutating touch, and the pins only agree because the HRW
    agrees."""
    from hashgraph_tpu.parallel.fleet import rendezvous_owner

    placement = uniform()
    for i in range(64):
        scope = f"match-{i}"
        host, shard = placement.owner(scope)
        assert shard == rendezvous_owner(scope, placement.shards_of(host))


def test_add_host_remaps_only_onto_new_host():
    placement = uniform()
    scopes = [f"elastic-{i}" for i in range(256)]
    before = {s: placement.owner(s) for s in scopes}
    placement.add_host("delta", ["delta:0", "delta:1"])
    after = {s: placement.owner(s) for s in scopes}
    moved = {s for s in scopes if before[s] != after[s]}
    assert moved, "a 4th host should win some scopes"
    for scope in moved:
        assert after[scope][0] == "delta", (scope, after[scope])


def test_remove_host_remaps_only_its_own_scopes():
    placement = uniform()
    scopes = [f"elastic-{i}" for i in range(256)]
    before = {s: placement.owner(s) for s in scopes}
    placement.remove_host("gamma")
    after = {s: placement.owner(s) for s in scopes}
    for scope in scopes:
        if before[scope][0] == "gamma":
            assert after[scope][0] != "gamma"
        else:
            assert after[scope] == before[scope], scope


def test_pins_survive_membership_changes():
    placement = uniform()
    host, shard = placement.owner("pinned-scope")
    placement.pin("pinned-scope", shard)
    placement.add_host("delta", ["delta:0"])
    assert placement.owner("pinned-scope") == (host, shard)
    placement.release("pinned-scope")


def test_remove_host_refuses_with_pinned_scopes():
    placement = uniform()
    host, shard = placement.owner("scope-0")  # gamma
    placement.pin("scope-0", shard)
    with pytest.raises(ValueError, match="live scopes"):
        placement.remove_host(host)
    placement.remove_host(host, force=True)
    assert host not in placement.host_ids


def test_migration_flips_atomically_no_dual_ownership():
    """Concurrent readers during a flip observe EXACTLY one of the two
    legal owners — never a third value, never an error; after the flip,
    only the new one. Pinned scopes follow their shard."""
    placement = uniform()
    host, shard = placement.owner("scope-1")  # alpha, alpha:0
    placement.pin("scope-1", shard)
    target = "beta"
    observed = set()
    errors = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                observed.add(placement.owner("scope-1"))
        except BaseException as exc:  # pragma: no cover - the failure
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for thread in threads:
        thread.start()
    placement.begin_migration(shard)
    assert placement.migrating(shard)
    placement.complete_migration(shard, target)
    stop.set()
    for thread in threads:
        thread.join()
    assert not errors
    assert observed <= {(host, shard), (target, shard)}, observed
    assert placement.owner("scope-1") == (target, shard)
    assert not placement.migrating(shard)
    assert shard in placement.shards_of(target)
    assert shard not in placement.shards_of(host)


def test_abort_migration_restores_routing():
    placement = uniform()
    _host, shard = placement.owner("scope-9")
    placement.begin_migration(shard, retry_after=0.5)
    assert placement.retry_after(shard) == 0.5
    placement.abort_migration(shard)
    assert not placement.migrating(shard)
    assert placement.owner("scope-9") == ("beta", shard)


def test_unpinned_scopes_avoid_empty_hosts():
    """A host whose shards all migrated away owns nothing at level 1."""
    placement = FederationPlacement.uniform(["a", "b"], 1)
    placement.begin_migration("a:0")
    placement.complete_migration("a:0", "b")
    for i in range(32):
        host, _shard = placement.owner(f"empty-{i}")
        assert host == "b"


def test_duplicate_shard_home_rejected():
    with pytest.raises(ValueError, match="two hosts"):
        FederationPlacement({"a": ["s:0"], "b": ["s:0"]})
