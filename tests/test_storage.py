"""Storage-contract tests (reference: tests/storage_stream_tests.rs):
stream/list/remove/replace, update error paths, empty-scope cleanup, and
scope-config validation paths.

Parametrized over every ConsensusStorage implementation — the in-memory
default and the device-pool-backed TpuBackedStorage must satisfy the same
contract."""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    InMemoryConsensusStorage,
    NetworkType,
    ScopeConfig,
)
from hashgraph_tpu.errors import (
    InvalidConsensusThreshold,
    InvalidMaxRounds,
    SessionNotFound,
)
from hashgraph_tpu.session import ConsensusSession

from common import NOW, make_service, random_stub_signer

SCOPE = "storage_scope"


def _tpu_backed():
    from hashgraph_tpu.engine import TpuBackedStorage

    return TpuBackedStorage(capacity=32, voter_capacity=8)


@pytest.fixture(params=["in_memory", "tpu_backed"])
def make_storage(request):
    """Storage factory, parametrized over every backend."""
    return InMemoryConsensusStorage if request.param == "in_memory" else _tpu_backed


def make_session(n=3, now=NOW) -> ConsensusSession:
    request = CreateProposalRequest(
        name="S",
        payload=b"",
        proposal_owner=random_stub_signer().identity(),
        expected_voters_count=n,
        expiration_timestamp=120,
        liveness_criteria_yes=True,
    )
    proposal = request.into_proposal(now)
    return ConsensusSession._new(proposal, ConsensusConfig.gossipsub(), now)


class TestSessionPrimitives:
    def test_save_get_remove(self, make_storage):
        storage = make_storage()
        session = make_session()
        pid = session.proposal.proposal_id
        storage.save_session(SCOPE, session)
        assert storage.get_session(SCOPE, pid).proposal.proposal_id == pid
        removed = storage.remove_session(SCOPE, pid)
        assert removed.proposal.proposal_id == pid
        assert storage.get_session(SCOPE, pid) is None
        assert storage.remove_session(SCOPE, pid) is None
        assert storage.remove_session("ghost", 1) is None

    def test_get_returns_snapshot_not_alias(self, make_storage):
        storage = make_storage()
        session = make_session()
        pid = session.proposal.proposal_id
        storage.save_session(SCOPE, session)
        snapshot = storage.get_session(SCOPE, pid)
        snapshot.proposal.name = "mutated"
        assert storage.get_session(SCOPE, pid).proposal.name == "S"

    def test_list_and_stream(self, make_storage):
        """reference: tests/storage_stream_tests.rs:42-127"""
        storage = make_storage()
        assert storage.list_scope_sessions(SCOPE) is None
        sessions = [make_session() for _ in range(3)]
        for s in sessions:
            storage.save_session(SCOPE, s)
        listed = storage.list_scope_sessions(SCOPE)
        assert {s.proposal.proposal_id for s in listed} == {
            s.proposal.proposal_id for s in sessions
        }
        streamed = list(storage.stream_scope_sessions(SCOPE))
        assert len(streamed) == 3
        assert list(storage.stream_scope_sessions("ghost")) == []

    def test_replace_scope_sessions(self, make_storage):
        storage = make_storage()
        storage.save_session(SCOPE, make_session())
        replacement = [make_session(), make_session()]
        storage.replace_scope_sessions(SCOPE, replacement)
        listed = storage.list_scope_sessions(SCOPE)
        assert {s.proposal.proposal_id for s in listed} == {
            s.proposal.proposal_id for s in replacement
        }

    def test_list_scopes(self, make_storage):
        storage = make_storage()
        assert storage.list_scopes() is None
        storage.save_session("a", make_session())
        storage.save_session("b", make_session())
        assert set(storage.list_scopes()) == {"a", "b"}

    def test_update_session_not_found(self, make_storage):
        """reference: tests/storage_stream_tests.rs:130-181"""
        storage = make_storage()
        with pytest.raises(SessionNotFound):
            storage.update_session(SCOPE, 42, lambda s: None)

    def test_update_session_mutation_persists_even_on_error(self, make_storage):
        # Mirrors the reference: the mutator runs on the stored value, so
        # state changes made before an error stick (Failed-on-cap semantics).
        storage = make_storage()
        session = make_session()
        pid = session.proposal.proposal_id
        storage.save_session(SCOPE, session)

        def mutator(s):
            s.proposal.name = "touched"
            raise ValueError("boom")

        with pytest.raises(ValueError):
            storage.update_session(SCOPE, pid, mutator)
        assert storage.get_session(SCOPE, pid).proposal.name == "touched"

    def test_update_scope_sessions_empty_removes_scope(self, make_storage):
        storage = make_storage()
        storage.save_session(SCOPE, make_session())

        storage.update_scope_sessions(SCOPE, lambda sessions: sessions.clear())
        assert storage.list_scope_sessions(SCOPE) is None
        assert storage.list_scopes() is None


class TestBackendEquivalenceEdges:
    """Regression: corner semantics where backends could diverge."""

    def test_update_scope_sessions_creates_scope_from_append(self, make_storage):
        storage = make_storage()
        session = make_session()
        storage.update_scope_sessions("fresh", lambda l: l.append(session))
        listed = storage.list_scope_sessions("fresh")
        assert listed is not None and len(listed) == 1

    def test_remove_last_session_keeps_empty_scope(self, make_storage):
        storage = make_storage()
        session = make_session()
        storage.save_session(SCOPE, session)
        storage.remove_session(SCOPE, session.proposal.proposal_id)
        assert storage.list_scope_sessions(SCOPE) == []

    def test_replace_with_empty_keeps_scope(self, make_storage):
        storage = make_storage()
        storage.save_session(SCOPE, make_session())
        storage.replace_scope_sessions(SCOPE, [])
        assert storage.list_scope_sessions(SCOPE) == []

    def test_save_overwrite_same_id_refreshes_everything(self, make_storage):
        storage = make_storage()
        first = make_session(n=3)
        pid = first.proposal.proposal_id
        storage.save_session(SCOPE, first)
        second = make_session(n=5)
        second.proposal.proposal_id = pid
        storage.save_session(SCOPE, second)
        stored = storage.get_session(SCOPE, pid)
        assert stored.proposal.expected_voters_count == 5
        # Device replica (when present) reflects the new session, not stale
        # config from the first save.
        if hasattr(storage, "device_state_of"):
            from hashgraph_tpu.ops import STATE_ACTIVE

            assert storage.device_state_of(SCOPE, pid) == STATE_ACTIVE
            slot = storage._slots[(SCOPE, pid)]
            assert int(storage.pool()._n[slot]) == 5

    def test_oversized_session_degrades_to_host_only(self):
        from hashgraph_tpu.engine import TpuBackedStorage

        storage = TpuBackedStorage(capacity=8, voter_capacity=4)
        big = make_session(n=3)
        pid = big.proposal.proposal_id
        storage.save_session(SCOPE, big)
        assert storage.device_state_of(SCOPE, pid) is not None

        # Mutate in more distinct voters than the pool has lanes: the
        # session stays queryable (host truth) with no stale device row.
        from hashgraph_tpu.wire import Vote

        def add_voters(s):
            for i in range(6):
                owner = bytes([50 + i]) * 4
                s.votes[owner] = Vote(vote_owner=owner, vote=True)

        storage.update_session(SCOPE, pid, add_voters)
        assert len(storage.get_session(SCOPE, pid).votes) == 6
        assert storage.device_state_of(SCOPE, pid) is None


class TestScopeConfigStorage:
    """reference: tests/storage_stream_tests.rs:184-244"""

    def test_get_set_roundtrip(self, make_storage):
        storage = make_storage()
        assert storage.get_scope_config(SCOPE) is None
        config = ScopeConfig(network_type=NetworkType.P2P, default_consensus_threshold=0.8)
        storage.set_scope_config(SCOPE, config)
        loaded = storage.get_scope_config(SCOPE)
        assert loaded.network_type == NetworkType.P2P
        assert loaded.default_consensus_threshold == 0.8
        # returned config is a snapshot
        loaded.default_consensus_threshold = 0.1
        assert storage.get_scope_config(SCOPE).default_consensus_threshold == 0.8

    def test_set_invalid_config_rejected(self, make_storage):
        storage = make_storage()
        bad = ScopeConfig(default_consensus_threshold=1.5)
        with pytest.raises(InvalidConsensusThreshold):
            storage.set_scope_config(SCOPE, bad)
        assert storage.get_scope_config(SCOPE) is None

    def test_update_creates_default_then_validates(self, make_storage):
        storage = make_storage()

        def updater(config):
            config.default_consensus_threshold = 0.9

        storage.update_scope_config(SCOPE, updater)
        assert storage.get_scope_config(SCOPE).default_consensus_threshold == 0.9

        def bad_updater(config):
            config.max_rounds_override = 0  # illegal for Gossipsub

        with pytest.raises(InvalidMaxRounds):
            storage.update_scope_config(SCOPE, bad_updater)

    def test_delete_scope_clears_config_and_sessions(self, make_storage):
        storage = make_storage()
        storage.save_session(SCOPE, make_session())
        storage.set_scope_config(SCOPE, ScopeConfig())
        storage.delete_scope(SCOPE)
        assert storage.list_scope_sessions(SCOPE) is None
        assert storage.get_scope_config(SCOPE) is None


class TestCustomStorageBackend:
    """The service is storage-agnostic: a dict-backed toy implementation
    satisfying the contract works end-to-end (role analogous to
    reference: tests/custom_scheme_tests.rs for the signer axis)."""

    def test_service_over_custom_storage(self, make_storage):
        class TracingStorage(InMemoryConsensusStorage):
            def __init__(self):
                super().__init__()
                self.saves = 0

            def save_session(self, scope, session):
                self.saves += 1
                return super().save_session(scope, session)

        storage = TracingStorage()
        from hashgraph_tpu import BroadcastEventBus, ConsensusService

        service = ConsensusService(storage, BroadcastEventBus(), random_stub_signer())
        request = CreateProposalRequest(
            name="x",
            payload=b"",
            proposal_owner=service.signer().identity(),
            expected_voters_count=1,
            expiration_timestamp=60,
            liveness_criteria_yes=True,
        )
        proposal = service.create_proposal(SCOPE, request, NOW)
        service.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
        assert storage.saves == 1
        assert storage.get_consensus_result(SCOPE, proposal.proposal_id) is True
