"""Persistent native verify pool: configuration, async submit/collect,
queue-depth telemetry, and equivalence of the async results with the
synchronous batch entry points. Skipped entirely when the native runtime
is unavailable (every caller has a pure-Python fallback)."""

import threading

import pytest

from hashgraph_tpu import native
from hashgraph_tpu.signing import Ed25519ConsensusSigner, EthereumConsensusSigner

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable"
)


@pytest.fixture(autouse=True)
def _restore_pool():
    yield
    native.pool_configure(0)  # hardware default back for other tests


class TestPoolConfig:
    def test_configure_and_size(self):
        assert native.pool_configure(2) == 2
        assert native.pool_size() == 2
        assert native.pool_configure(1) == 1
        assert native.pool_size() == 1
        # <= 0 restores the hardware default (>= 1).
        assert native.pool_configure(0) >= 1

    def test_queue_depth_idle(self):
        assert native.pool_queue_depth() == 0
        # The metrics-safe readout never triggers a load; the runtime is
        # already loaded here, so it reports the same number.
        assert native.pool_queue_depth_if_loaded() == 0

    def test_wait_unknown_handle_is_error_not_hang(self):
        lib = native._load()
        assert lib.hg_pool_wait(999_999_999) == 1


class TestAsyncSubmit:
    def test_eth_submit_matches_sync(self):
        signers = [EthereumConsensusSigner.random() for _ in range(3)]
        payloads = [b"p%d" % i for i in range(24)]
        idents = [signers[i % 3].identity() for i in range(24)]
        sigs = [signers[i % 3].sign(p) for i, p in enumerate(payloads)]
        sigs[5] = bytes([sigs[5][0] ^ 1]) + sigs[5][1:]
        sigs[6] = sigs[6][:64] + b"\x09"  # malformed recovery byte
        job = native.eth_verify_batch_submit(idents, payloads, sigs)
        assert job is not None
        sync = native.eth_verify_batch(idents, payloads, sigs)
        assert list(job.collect()) == list(sync)
        # collect() is idempotent.
        assert list(job.collect()) == list(sync)

    def test_ed25519_submit_matches_sync(self):
        signers = [Ed25519ConsensusSigner.random() for _ in range(3)]
        payloads = [b"p%d" % i for i in range(24)]
        idents = [signers[i % 3].identity() for i in range(24)]
        sigs = [signers[i % 3].sign(p) for i, p in enumerate(payloads)]
        sigs[7] = bytes([sigs[7][0] ^ 1]) + sigs[7][1:]
        job = native.ed25519_verify_batch_submit(idents, payloads, sigs)
        assert job is not None
        sync = native.ed25519_verify_batch(idents, payloads, sigs)
        assert list(job.collect()) == list(sync)

    def test_many_overlapping_jobs(self):
        """Several in-flight jobs complete independently and correctly
        regardless of collect order."""
        signer = Ed25519ConsensusSigner.random()
        jobs = []
        for j in range(6):
            payloads = [b"j%d-%d" % (j, i) for i in range(32)]
            sigs = [signer.sign(p) for p in payloads]
            jobs.append(
                native.ed25519_verify_batch_submit(
                    [signer.identity()] * 32, payloads, sigs
                )
            )
        for job in reversed(jobs):
            assert list(job.collect()) == [1] * 32

    def test_submit_from_threads(self):
        signer = Ed25519ConsensusSigner.random()
        payloads = [b"t%d" % i for i in range(16)]
        sigs = [signer.sign(p) for p in payloads]
        errors = []

        def worker():
            try:
                job = native.ed25519_verify_batch_submit(
                    [signer.identity()] * 16, payloads, sigs
                )
                assert list(job.collect()) == [1] * 16
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_single_thread_pool_still_completes(self):
        native.pool_configure(1)
        signer = Ed25519ConsensusSigner.random()
        payloads = [b"s%d" % i for i in range(8)]
        sigs = [signer.sign(p) for p in payloads]
        job = native.ed25519_verify_batch_submit(
            [signer.identity()] * 8, payloads, sigs
        )
        assert list(job.collect()) == [1] * 8


class TestSchemeSubmitFallback:
    def test_stub_default_defers_to_collect(self):
        """Schemes without a native path get the deferred-sync default —
        identical verdicts, no pool involvement."""
        from hashgraph_tpu.signing import StubConsensusSigner

        s = StubConsensusSigner(b"\x01" * 20)
        payloads = [b"a", b"b"]
        sigs = [s.sign(p) for p in payloads]
        pend = StubConsensusSigner.verify_batch_submit(
            [s.identity()] * 2, payloads, sigs
        )
        assert pend.collect() == [True, True]
