"""Liveness observatory: φ-accrual suspicion, adaptive consensus
timeouts, overload admission control, and the machine-checked liveness
verdict.

Covers the full stack ISSUE 18 added: the accrual math (obs/accrual),
its integration into the health watchdog (stale-OR-phi flagging, read-
time grading so convictions clear on heal), the per-scope adaptive
timeout learner and its engine wiring, the ScopeConfig/WAL plumbing
that persists timeout bounds, RETRY_AFTER shedding on the bridge plus
the gossip node's deferral window, and the sim-layer liveness verdict
with its A/B override seam.
"""

import math

import pytest

from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.engine.adaptive import AdaptiveTimeoutBook
from hashgraph_tpu.obs.accrual import (
    DEFAULT_MAX_PHI,
    PhiAccrual,
    phi_from_deviation,
)
from hashgraph_tpu.obs.health import DEFAULT_PHI_THRESHOLD, HealthMonitor
from hashgraph_tpu.obs.registry import MetricsRegistry
from hashgraph_tpu.scope_config import ScopeConfig, ScopeConfigBuilder

from common import NOW, random_stub_signer


# ── φ-accrual math ─────────────────────────────────────────────────────


def test_phi_from_deviation_shape():
    assert phi_from_deviation(0.0) == 0.0
    assert phi_from_deviation(-3.0) == 0.0
    # Monotone non-decreasing across the erfc/asymptotic switch at x=8.
    xs = [0.5, 1.0, 2.0, 4.0, 7.9, 8.0, 8.1, 20.0, 37.0, 40.0, 100.0]
    phis = [phi_from_deviation(x) for x in xs]
    assert phis == sorted(phis)
    assert all(math.isfinite(p) for p in phis)
    # phi=1 means "~10% of intervals run this late": Q(x)=0.1 at x≈1.2816.
    assert phi_from_deviation(1.2816) == pytest.approx(1.0, abs=1e-3)
    # Clamped: a silence 100 sigmas out is operationally identical to 64.
    assert phi_from_deviation(100.0) == DEFAULT_MAX_PHI
    assert phi_from_deviation(100.0, max_phi=10.0) == 10.0


def test_phi_accrual_min_samples_gate():
    acc = PhiAccrual(min_samples=8)
    now = 0.0
    for _ in range(8):  # 8 heartbeats = 7 intervals < min_samples
        now += 10.0
        acc.heartbeat(now)
    assert acc.sample_count == 7
    assert acc.phi(now + 1_000.0) == 0.0
    acc.heartbeat(now + 10.0)  # 8th interval: distribution trusted
    assert acc.phi(now + 1_010.0) > 0.0


def test_phi_accrual_monotone_in_silence_and_resets_on_heartbeat():
    acc = PhiAccrual()
    now = 0.0
    for _ in range(16):
        now += 10.0
        acc.heartbeat(now)
    prev = -1.0
    for silence in range(0, 200, 5):
        cur = acc.phi(now + silence)
        assert cur >= prev
        prev = cur
    assert prev > DEFAULT_PHI_THRESHOLD  # long silence convicts
    acc.heartbeat(now + 200.0)
    assert acc.phi(now + 200.0) == 0.0  # suspicion revised instantly


def test_phi_accrual_same_tick_coalesces_and_window_bounds():
    acc = PhiAccrual(window=4)
    acc.heartbeat(5.0)
    for _ in range(10):  # a burst in one batch is ONE observation
        acc.heartbeat(5.0)
    assert acc.sample_count == 0
    for i in range(50):
        acc.heartbeat(5.0 + (i + 1) * 3.0)
    assert acc.sample_count == 4  # bounded history
    assert acc.mean() == pytest.approx(3.0)


def test_phi_accrual_jitter_earns_wider_tolerance():
    """A peer with jittery arrivals must be suspected LESS at the same
    silence than a metronome-regular peer with the same mean — the whole
    point of replacing one fixed bar with per-peer distributions."""
    regular, jittery = PhiAccrual(), PhiAccrual()
    now_r = now_j = 0.0
    for i in range(32):
        now_r += 10.0
        regular.heartbeat(now_r)
        now_j += 10.0 + (6.0 if i % 2 else -6.0)  # mean 10, wide spread
        jittery.heartbeat(now_j)
    silence = 40.0
    assert jittery.phi(now_j + silence) < regular.phi(now_r + silence)
    # The metronome still gets the variance floor: one tick late is not
    # certain death.
    assert regular.phi(now_r + 10.5) < DEFAULT_PHI_THRESHOLD


# ── watchdog integration (stale OR phi, read-time grading) ─────────────


def _monitor(**kw) -> HealthMonitor:
    kw.setdefault("registry", MetricsRegistry())
    return HealthMonitor(**kw)


def test_watchdog_flags_phi_before_binary_floor():
    mon = _monitor(stale_after=10_000.0)
    peer = b"\x01" * 32
    now = 0
    for _ in range(16):
        now += 10
        mon.note_admitted({peer: 1}, now)
    # Silence far past the peer's own cadence but far under the binary
    # floor: only the φ detector can see it.
    probe = now + 500
    assert peer.hex() in mon.watchdog(now=probe)
    card = mon.snapshot(now=probe)["peers"][peer.hex()]
    assert card["phi"] >= card["phi_threshold"]
    # The binary floor itself is untouched — the silence is well inside
    # stale_after, so the conviction is the φ detector's alone.
    assert probe - card["last_seen"] <= card["stale_after"]
    # Read-time grading: a heartbeat clears the conviction with no
    # explicit reset call anywhere.
    mon.note_admitted({peer: 1}, probe)
    assert peer.hex() not in mon.watchdog(now=probe)


def test_phi_threshold_none_disables_accrual_convictions():
    mon = _monitor(stale_after=10_000.0, phi_threshold=None)
    peer = b"\x02" * 32
    now = 0
    for _ in range(16):
        now += 10
        mon.note_admitted({peer: 1}, now)
    assert mon.watchdog(now=now + 500) == []  # binary floor only
    assert peer.hex() in mon.watchdog(now=now + 20_000)


# ── adaptive timeout learner ───────────────────────────────────────────


def _adaptive_config(lo=1.0, hi=60.0, default=30.0) -> ScopeConfig:
    return (
        ScopeConfigBuilder()
        .p2p_preset()
        .with_timeout(default)
        .with_timeout_bounds(lo, hi)
        .build()
    )


def test_book_noop_without_bounds():
    book = AdaptiveTimeoutBook()
    static = ScopeConfigBuilder().p2p_preset().build()
    assert book.current("s", static) is None
    assert book.on_timeout("s", static) is None
    assert book.on_decided("s", static, 1.0) is None
    assert book.current("s", None) is None
    assert book.snapshot()["scopes"] == {}


def test_book_backoff_and_decay():
    book = AdaptiveTimeoutBook()
    cfg = _adaptive_config(lo=1.0, hi=60.0, default=4.0)
    assert book.current("s", cfg) == 4.0  # seeds at the static default
    assert book.on_timeout("s", cfg) == 8.0  # geometric backoff
    assert book.on_timeout("s", cfg) == 16.0
    for _ in range(10):
        book.on_timeout("s", cfg)
    assert book.current("s", cfg) == 60.0  # clamped at timeout_max
    # Successes decay toward observed_p99 * headroom from above.
    target = 2.0 * book.headroom
    prev = book.current("s", cfg)
    for _ in range(50):
        cur = book.on_decided("s", cfg, 2.0)
        assert cur <= prev
        prev = cur
    assert prev == pytest.approx(target, rel=0.05)
    # A zero observation (empty SLO window) must never drag the value.
    assert book.on_decided("s", cfg, 0.0) == prev
    snap = book.snapshot()
    assert snap["backoffs_total"] == 12 and snap["decays_total"] == 50


def test_book_lru_bound():
    book = AdaptiveTimeoutBook(max_scopes=4)
    cfg = _adaptive_config()
    for i in range(32):
        book.on_timeout(f"scope-{i}", cfg)
    assert len(book.snapshot()["scopes"]) == 4
    assert "scope-31" in book.snapshot()["scopes"]


def test_book_ctor_validation():
    with pytest.raises(ValueError):
        AdaptiveTimeoutBook(backoff=1.0)
    with pytest.raises(ValueError):
        AdaptiveTimeoutBook(decay=0.0)
    with pytest.raises(ValueError):
        AdaptiveTimeoutBook(headroom=0.9)


# ── ScopeConfig bounds + WAL persistence ───────────────────────────────


def test_scope_config_bounds_validation():
    with pytest.raises(ValueError):
        ScopeConfigBuilder().p2p_preset().with_timeout_bounds(
            1.0, None
        ).build()
    with pytest.raises(ValueError):
        ScopeConfigBuilder().p2p_preset().with_timeout_bounds(
            None, 30.0
        ).build()
    with pytest.raises(ValueError):
        ScopeConfigBuilder().p2p_preset().with_timeout_bounds(
            30.0, 1.0
        ).build()
    assert not ScopeConfigBuilder().p2p_preset().build().adaptive_timeout_enabled()
    assert _adaptive_config().adaptive_timeout_enabled()


def test_wal_codec_round_trips_timeout_bounds():
    from hashgraph_tpu.wal.format import (
        Reader,
        decode_scope_config,
        encode_scope_config,
    )

    for cfg in (
        _adaptive_config(lo=0.25, hi=12.5),
        ScopeConfigBuilder().gossipsub_preset().build(),
    ):
        blob = encode_scope_config(cfg)
        out = decode_scope_config(Reader(blob))
        assert out.timeout_min == cfg.timeout_min
        assert out.timeout_max == cfg.timeout_max
        assert out.adaptive_timeout_enabled() == cfg.adaptive_timeout_enabled()
        # Canonical: fingerprints hash these bytes.
        assert encode_scope_config(out) == blob


# ── engine wiring ──────────────────────────────────────────────────────


def test_engine_adaptive_timeout_learns_from_fired_timeouts():
    from hashgraph_tpu import CreateProposalRequest

    engine = TpuConsensusEngine(
        random_stub_signer(), capacity=16, voter_capacity=8
    )
    scope = "adaptive-scope"
    engine.set_scope_config(scope, _adaptive_config(lo=1.0, hi=60.0, default=5.0))
    assert engine.adaptive_timeout(scope) == 5.0
    # Static scope: the advisory readout is the config default, always.
    engine.set_scope_config("static", ScopeConfigBuilder().p2p_preset().build())
    static_default = engine.get_scope_config("static").default_timeout
    assert engine.adaptive_timeout("static") == static_default

    proposal = engine.create_proposal(
        scope,
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=4,
            expiration_timestamp=100,
            liveness_criteria_yes=False,
        ),
        NOW,
    )
    # 0 of 4 votes, liveness False: the timeout decides False
    # (silent-as-no), and the FIRED timeout is the learning signal.
    assert (
        engine.handle_consensus_timeout(scope, proposal.proposal_id, NOW + 60)
        is False
    )
    # The fired timeout backed the scope's learned value off.
    assert engine.adaptive_timeout(scope) == 10.0
    snap = engine.adaptive_timeout_snapshot()
    assert snap["backoffs_total"] == 1
    assert snap["scopes"][scope] == 10.0


# ── overload admission: bridge shed + gossip deferral ──────────────────


class _FakeConn:
    def __init__(self):
        self.sent = b""

    def sendall(self, data: bytes) -> None:
        self.sent += data


def test_bridge_sheds_retry_after_past_admission_limit():
    import threading

    from hashgraph_tpu.bridge import protocol as P
    from hashgraph_tpu.bridge.server import BridgeServer

    server = BridgeServer(
        capacity=4, voter_capacity=4, ordered_admission_limit=2
    )

    class _Lane:
        def __init__(self, depth: int):
            self._depth = depth

        def depth(self) -> int:
            return self._depth

    class _State:
        def __init__(self, depth: int):
            self.write_lock = threading.Lock()
            self.ordered = _Lane(depth)

    mutating = next(iter(P.MUTATING_OPCODES))
    read_only = next(
        op for op in range(64) if op not in P.MUTATING_OPCODES
    )
    # Below the limit, and for read-only frames at ANY depth: admitted.
    conn = _FakeConn()
    assert not server._shed_retry_after(conn, _State(1), mutating, 7)
    assert not server._shed_retry_after(conn, _State(500), read_only, 7)
    assert conn.sent == b""
    # At the limit: shed with a typed, depth-scaled hint.
    assert server._shed_retry_after(conn, _State(2), mutating, 7)
    status, corr, cursor = P.parse_frame(conn.sent[4:], tagged=True)
    assert status == P.STATUS_RETRY_AFTER
    assert corr == 7
    hint = float(cursor.string())
    assert 0.0 < hint <= 1.0


def test_gossip_node_defers_during_retry_after_window():
    from hashgraph_tpu.bridge import protocol as P
    from hashgraph_tpu.bridge.client import BridgeError
    from hashgraph_tpu.gossip.node import GossipNode

    class _Transport:
        def __init__(self):
            self.requests = 0

        def try_request(self, name, opcode, payload):
            self.requests += 1
            return None  # backpressure-shed; irrelevant to this test

        def stats(self):
            return {}

        def close(self):
            pass

    class _RetryAfterFuture:
        def result(self, timeout=None):
            raise BridgeError(P.STATUS_RETRY_AFTER, "0.5")

    transport = _Transport()
    node = GossipNode("n0", transport=transport)
    meta = [(1, "scope-a", 3)]
    # A typed shed opens the peer's backoff window and books the frame
    # as deferred (not failed) with its scopes dirty for anti-entropy.
    node._harvest("peer-1", meta, _RetryAfterFuture(), None)
    assert node._retry_after["peer-1"] > 0
    assert node._deferred_frames == 1
    assert node._failed_frames == 0
    assert node._dirty["peer-1"] == {"scope-a"}
    # While the window is open, hot-path frames defer WITHOUT touching
    # the wire — the node must not re-offer load the peer just shed.
    node._send_frame("peer-1", b"payload", meta)
    assert transport.requests == 0
    assert node._deferred_frames == 2
    # A garbled hint falls back to a short fixed window, never a crash.
    class _GarbledFuture:
        def result(self, timeout=None):
            raise BridgeError(P.STATUS_RETRY_AFTER, "not-a-float")

    node._harvest("peer-2", meta, _GarbledFuture(), None)
    assert node._retry_after["peer-2"] > 0


# ── sim layer: liveness verdict + A/B override seam ────────────────────


def test_flapping_links_scenario_and_static_baseline_arm():
    from hashgraph_tpu.sim import run_scenario

    run = run_scenario("flapping-links", 7)
    assert run["passed"], run["checks"]
    live = run["verdicts"]["liveness"]
    assert live["ok"]
    assert live["stale_convictions"] == {}
    assert live["undecidable_sessions"] == 0
    assert 0 < live["max_decide_ticks"] <= live["decide_bound_ticks"]
    assert run["checks"]["phi_suspected_during_flap"]

    # The A/B seam bench.py liveness rides: same scenario, binary-floor-
    # only watchdog. All four verdicts still hold — the arm is blind to
    # the flap (sub-floor silence), not broken.
    base = run_scenario(
        "flapping-links", 7, overrides={"phi_threshold": None}
    )
    assert all(v["ok"] for v in base["verdicts"].values())
    assert not base["checks"]["phi_suspected_during_flap"]
    assert base["verdicts"]["liveness"]["stale_convictions"] == {}


def test_slow_never_dead_scenario_counterfactual():
    from hashgraph_tpu.sim import run_scenario

    run = run_scenario("slow-never-dead", 7)
    assert run["passed"], run["checks"]
    # The variance-aware detector tolerates the slow-but-alive peer; the
    # tight-static counterfactual (computed inside the scenario) would
    # have convicted it.
    assert run["checks"]["slow_peer_never_suspected"]
    assert run["checks"]["metronome_counterfactual_convicts"]
