"""Batched proposal ingest: parity with sequential process_incoming_proposal.

The batched path injects bulk-verified signatures and device chain results
into the exact scalar check sequence — statuses, registered state, and
events must match a scalar engine fed the same proposals one at a time.
"""

import numpy as np
import pytest

from hashgraph_tpu import (
    ConsensusError,
    CreateProposalRequest,
    StatusCode,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine

from common import NOW, random_stub_signer


def make_carried_proposal(n=3, votes=2, seed=0, name="p", mutate=None):
    """A proposal carrying a valid embedded chain of `votes` votes."""
    rng = np.random.default_rng(seed)
    signers = [random_stub_signer() for _ in range(max(votes, 1))]
    proposal = CreateProposalRequest(
        name, b"", b"o", n, 1000, True
    ).into_proposal(NOW)
    for i in range(votes):
        vote = build_vote(proposal, bool(rng.random() < 0.7), signers[i], NOW + i)
        proposal.votes.append(vote)
    if mutate:
        mutate(proposal)
    return proposal


def drain(receiver):
    out = []
    while (item := receiver.try_recv()) is not None:
        out.append(item)
    return out


class TestBatchProposalIngest:
    def test_mixed_batch_parity(self):
        signer = random_stub_signer()
        scalar = TpuConsensusEngine(signer, capacity=32, voter_capacity=8)
        batch = TpuConsensusEngine(signer, capacity=32, voter_capacity=8)
        scalar_rx = scalar.event_bus().subscribe()
        batch_rx = batch.event_bus().subscribe()

        def bad_sig(p):
            p.votes[1].signature = bytes(len(p.votes[1].signature))

        def bad_chain(p):
            p.votes[1].received_hash = b"\x13" * 32

        def bad_pid(p):
            p.votes[0].proposal_id ^= 0xFF

        proposals = [
            make_carried_proposal(3, 0, 0, "empty"),
            make_carried_proposal(3, 2, 1, "decides"),  # 2/3 quorum -> decided
            make_carried_proposal(5, 2, 2, "inflight"),
            make_carried_proposal(3, 2, 3, "forged", mutate=bad_sig),
            make_carried_proposal(3, 2, 4, "badchain", mutate=bad_chain),
            make_carried_proposal(3, 1, 5, "badpid", mutate=bad_pid),
        ]
        # Duplicate of the first (same proposal_id) appended.
        proposals.append(proposals[0].clone())

        expected = []
        for p in proposals:
            try:
                scalar.process_incoming_proposal("s", p.clone(), NOW + 10)
                expected.append(int(StatusCode.OK))
            except ConsensusError as exc:
                expected.append(int(exc.code))

        statuses = batch.ingest_proposals(
            [("s", p.clone()) for p in proposals], NOW + 10
        )
        assert statuses == expected, (statuses, expected)

        # Registered sessions and their states match.
        s_stats = scalar.get_scope_stats("s")
        b_stats = batch.get_scope_stats("s")
        assert (s_stats.total_sessions, s_stats.consensus_reached) == (
            b_stats.total_sessions,
            b_stats.consensus_reached,
        )
        for p in proposals[:3]:
            assert (
                scalar.export_session("s", p.proposal_id).state
                == batch.export_session("s", p.proposal_id).state
            )
        assert drain(scalar_rx) == drain(batch_rx)

    def test_continues_after_batch_load(self):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        p = make_carried_proposal(3, 1, seed=9)
        [status] = engine.ingest_proposals([("s", p)], NOW + 1)
        assert status == int(StatusCode.OK)
        # One more YES decides (embedded vote was YES with seed 9? force it).
        v = build_vote(
            engine.get_proposal("s", p.proposal_id), True, random_stub_signer(), NOW + 2
        )
        engine.process_incoming_vote("s", v, NOW + 2)
        session = engine.export_session("s", p.proposal_id)
        assert len(session.votes) == 2
