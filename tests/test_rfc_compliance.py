"""RFC-keyed protocol invariants (reference: tests/rfc_compliance_tests.rs).

Round initialization/increment semantics, gossipsub round-2 behavior, P2P
dynamic caps, batch vote processing, n<=2 unanimity, majority rules, expiry,
replay protection, and vote-equality handling.
"""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    build_vote,
    compute_vote_hash,
)
from hashgraph_tpu.errors import (
    ConsensusNotReached,
    ProposalExpired,
    TimestampOlderThanCreationTime,
    VoteExpired,
)

from common import (
    NOW,
    cast_remote_vote,
    cast_remote_vote_and_get_proposal,
    make_service,
    random_stub_signer,
)

SCOPE = "rfc_compliance_scope"
EXPIRATION = 120


def create(service, scope, n, config, liveness=True, now=NOW, name="RFC Test"):
    request = CreateProposalRequest(
        name=name,
        payload=b"",
        proposal_owner=random_stub_signer().identity(),
        expected_voters_count=n,
        expiration_timestamp=EXPIRATION,
        liveness_criteria_yes=liveness,
    )
    return service.create_proposal_with_config(scope, request, config, now)


class TestRoundSemantics:
    def test_proposal_initialization_round_is_one(self):
        service = make_service()
        proposal = create(service, SCOPE, 3, ConsensusConfig.gossipsub())
        assert proposal.round == 1

    def test_round_increments_on_vote_p2p(self):
        service = make_service()
        proposal = create(service, SCOPE, 3, ConsensusConfig.p2p())
        assert proposal.round == 1
        proposal = cast_remote_vote_and_get_proposal(
            service, SCOPE, proposal.proposal_id, True, random_stub_signer()
        )
        assert proposal.round == 2
        proposal = cast_remote_vote_and_get_proposal(
            service, SCOPE, proposal.proposal_id, True, random_stub_signer()
        )
        assert proposal.round == 3

    def test_gossipsub_rounds_stay_at_two(self):
        service = make_service()
        proposal = create(service, SCOPE, 5, ConsensusConfig.gossipsub())
        for i in range(3):
            proposal = cast_remote_vote_and_get_proposal(
                service, SCOPE, proposal.proposal_id, True, random_stub_signer()
            )
            assert proposal.round == 2
            assert len(proposal.votes) == i + 1

    def test_gossipsub_allows_multiple_votes_in_round_two(self):
        service = make_service()
        proposal = create(service, SCOPE, 12, ConsensusConfig.gossipsub())
        for _ in range(7):
            proposal = cast_remote_vote_and_get_proposal(
                service, SCOPE, proposal.proposal_id, True, random_stub_signer()
            )
            assert proposal.round == 2
        assert len(proposal.votes) == 7

    def test_p2p_dynamic_max_rounds(self):
        # n=9: cap = ceil(2*9/3) = 6 votes; final round = 7; consensus YES.
        service = make_service()
        proposal = create(service, SCOPE, 9, ConsensusConfig.p2p())
        for i in range(6):
            proposal = cast_remote_vote_and_get_proposal(
                service, SCOPE, proposal.proposal_id, True, random_stub_signer()
            )
            assert proposal.round == i + 2
        assert len(proposal.votes) == 6
        assert proposal.round == 7
        assert service.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True

    @pytest.mark.parametrize(
        "n,max_votes",
        [(1, 1), (2, 2), (3, 2), (4, 3), (5, 4), (6, 4), (7, 5), (8, 6), (9, 6), (10, 7)],
    )
    def test_p2p_ceil_calculation_edge_cases(self, n, max_votes):
        """Live sessions must admit exactly ceil(2n/3) votes in P2P mode.
        (Sessions may reach consensus mid-way; vote count still proceeds to
        the cap since add_vote on a reached session is a no-op success.)"""
        service = make_service()
        proposal = create(service, SCOPE, n, ConsensusConfig.p2p(), name=f"n={n}")
        accepted = 0
        for _ in range(max_votes):
            proposal_snapshot = service.storage().get_proposal(SCOPE, proposal.proposal_id)
            vote = build_vote(proposal_snapshot, True, random_stub_signer(), NOW)
            service.process_incoming_vote(SCOPE, vote, NOW)
            accepted += 1
        final = service.storage().get_proposal(SCOPE, proposal.proposal_id)
        assert accepted == max_votes
        # All unanimous-YES runs reach consensus at/ before the cap, so votes
        # stop being inserted once reached; the cap was never exceeded.
        assert len(final.votes) <= max_votes


class TestBatchProcessing:
    def test_gossipsub_batch_vote_processing(self):
        service = make_service()
        scope = "batch_gossipsub"
        request = CreateProposalRequest(
            name="Batch",
            payload=b"",
            proposal_owner=random_stub_signer().identity(),
            expected_voters_count=5,
            expiration_timestamp=EXPIRATION,
            liveness_criteria_yes=True,
        )
        proposal = request.into_proposal(NOW)
        for i in range(3):
            vote = build_vote(proposal, True, random_stub_signer(), NOW)
            proposal.votes.append(vote)
            proposal.round = 2

        service.process_incoming_proposal(scope, proposal.clone(), NOW)
        final = cast_remote_vote_and_get_proposal(
            service, scope, proposal.proposal_id, True, random_stub_signer()
        )
        assert final.round == 2
        assert len(final.votes) == 4

    def test_p2p_batch_vote_processing(self):
        service = make_service()
        scope = "batch_p2p"
        request = CreateProposalRequest(
            name="Batch",
            payload=b"",
            proposal_owner=random_stub_signer().identity(),
            expected_voters_count=9,
            expiration_timestamp=EXPIRATION,
            liveness_criteria_yes=True,
        )
        proposal = request.into_proposal(NOW)
        for i in range(6):
            vote = build_vote(proposal, True, random_stub_signer(), NOW)
            proposal.votes.append(vote)
            proposal.round = i + 2

        service.process_incoming_proposal(scope, proposal.clone(), NOW)
        assert service.storage().get_consensus_result(scope, proposal.proposal_id) is True

        # Further votes cannot change the decided result.
        cast_remote_vote(service, scope, proposal.proposal_id, False, random_stub_signer())
        assert service.storage().get_consensus_result(scope, proposal.proposal_id) is True


class TestConsensusRules:
    def test_consensus_reachable_in_both_modes(self):
        service = make_service()
        for scope, config in [
            ("gossipsub_consensus", ConsensusConfig.gossipsub()),
            ("p2p_consensus", ConsensusConfig.p2p()),
        ]:
            proposal = create(service, scope, 6, config)
            for _ in range(4):
                cast_remote_vote(
                    service, scope, proposal.proposal_id, True, random_stub_signer()
                )
            assert service.storage().get_consensus_result(scope, proposal.proposal_id) is True

    def test_n_le_2_requires_unanimous_yes(self):
        service = make_service()
        # n=1: single YES decides immediately.
        p1 = create(service, "n1", 1, ConsensusConfig.gossipsub())
        cast_remote_vote(service, "n1", p1.proposal_id, True, random_stub_signer())
        assert service.storage().get_consensus_result("n1", p1.proposal_id) is True

        # n=2 both YES -> True.
        p2 = create(service, "n2", 2, ConsensusConfig.gossipsub())
        cast_remote_vote(service, "n2", p2.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, "n2", p2.proposal_id, True, random_stub_signer())
        assert service.storage().get_consensus_result("n2", p2.proposal_id) is True

        # n=2 one YES one NO -> False (non-unanimous).
        p3 = create(service, "n3", 2, ConsensusConfig.gossipsub())
        cast_remote_vote(service, "n3", p3.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, "n3", p3.proposal_id, False, random_stub_signer())
        assert service.storage().get_consensus_result("n3", p3.proposal_id) is False

    def test_n_gt_2_consensus_requirements(self):
        service = make_service()
        proposal = create(service, SCOPE, 3, ConsensusConfig.gossipsub())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        with pytest.raises(ConsensusNotReached):
            service.storage().get_consensus_result(SCOPE, proposal.proposal_id)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        assert service.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True


class TestExpiryAndReplay:
    def test_expired_proposal_rejected(self):
        service = make_service()
        request = CreateProposalRequest(
            name="Expires",
            payload=b"",
            proposal_owner=random_stub_signer().identity(),
            expected_voters_count=3,
            expiration_timestamp=1,
            liveness_criteria_yes=True,
        )
        proposal = service.create_proposal_with_config(
            SCOPE, request, ConsensusConfig.gossipsub(), NOW
        )
        # 2 seconds later the proposal (1s lifetime) is expired.
        with pytest.raises((ProposalExpired, VoteExpired)):
            cast_remote_vote(
                service, SCOPE, proposal.proposal_id, True, random_stub_signer(), now=NOW + 2
            )

    def test_timestamp_replay_attack_protection(self):
        service = make_service()
        proposal = create(service, SCOPE, 3, ConsensusConfig.gossipsub())
        proposal = cast_remote_vote_and_get_proposal(
            service, SCOPE, proposal.proposal_id, True, random_stub_signer()
        )

        voter = random_stub_signer()
        vote = build_vote(proposal, True, voter, NOW)
        # Rewind the timestamp to before proposal creation and re-sign.
        vote.timestamp = NOW - EXPIRATION * 2
        vote.vote_hash = compute_vote_hash(vote)
        vote.signature = voter.sign(vote.signing_payload())

        with pytest.raises(TimestampOlderThanCreationTime):
            service.process_incoming_vote(SCOPE, vote, NOW)


class TestEqualityOfVotes:
    @pytest.mark.parametrize("liveness,expected", [(True, True), (False, False)])
    def test_equality_resolved_by_liveness(self, liveness, expected):
        service = make_service()
        scope = f"equality_{liveness}"
        proposal = create(service, scope, 4, ConsensusConfig.gossipsub(), liveness=liveness)
        for choice in (True, True, False, False):
            cast_remote_vote(service, scope, proposal.proposal_id, choice, random_stub_signer())
        assert (
            service.storage().get_consensus_result(scope, proposal.proposal_id) is expected
        )
