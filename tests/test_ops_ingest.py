"""Parity fuzz: batched ingest kernel vs sequential scalar ``add_vote``.

Random traces over a pool of proposals with mixed modes/thresholds/expiry,
including duplicate voters, round-cap overruns, mid-batch consensus cuts, and
votes to decided/failed sessions. The device statuses, tallies, masks, and
final states must match the scalar session engine exactly, vote by vote.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from hashgraph_tpu import ConsensusConfig, CreateProposalRequest
from hashgraph_tpu.errors import (
    ConsensusError,
    StatusCode,
)
from hashgraph_tpu.ops import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    required_votes_np,
)
from hashgraph_tpu.ops.ingest import (
    PAD_STATUS,
    group_batch,
    ingest_kernel,
    pack_grid,
    pack_slots,
)
from hashgraph_tpu.session import ConsensusSession
from hashgraph_tpu.wire import Vote

NOW = 1_700_000_000
V_CAP = 16  # voter capacity per proposal in these tests


def scalar_state_code(session: ConsensusSession) -> int:
    if session.state.is_active:
        return STATE_ACTIVE
    if session.state.is_failed:
        return STATE_FAILED
    return STATE_REACHED_YES if session.state.result else STATE_REACHED_NO


def apply_scalar(session: ConsensusSession, voter: int, val: bool, now: int) -> int:
    """Run one add_vote on the oracle; return the equivalent status code."""
    before = len(session.votes)
    vote = Vote(vote_owner=bytes([voter + 1]), vote=val, proposal_id=session.proposal.proposal_id)
    try:
        session.add_vote(vote, now)
    except ConsensusError as exc:
        return int(exc.code)
    if len(session.votes) == before:
        return int(StatusCode.ALREADY_REACHED)
    return int(StatusCode.OK)


def make_pool(configs):
    """Build device pool arrays + scalar oracle sessions from per-slot specs:
    (n, mode, liveness, threshold, expiration_offset)."""
    p_count = len(configs)
    state = np.full(p_count, STATE_ACTIVE, np.int32)
    yes = np.zeros(p_count, np.int32)
    tot = np.zeros(p_count, np.int32)
    vote_mask = np.zeros((p_count, V_CAP), bool)
    vote_val = np.zeros((p_count, V_CAP), bool)
    n_arr = np.zeros(p_count, np.int32)
    req = np.zeros(p_count, np.int32)
    cap = np.zeros(p_count, np.int32)
    gossip = np.zeros(p_count, bool)
    liveness = np.zeros(p_count, bool)
    expiry = np.zeros(p_count, np.int64)
    sessions = []

    for i, (n, mode, live, threshold, exp_off) in enumerate(configs):
        config = (
            ConsensusConfig.gossipsub() if mode == "gossipsub" else ConsensusConfig.p2p()
        ).with_threshold(threshold)
        request = CreateProposalRequest(
            name=f"p{i}",
            payload=b"",
            proposal_owner=b"owner",
            expected_voters_count=n,
            expiration_timestamp=exp_off,
            liveness_criteria_yes=live,
        )
        proposal = request.into_proposal(NOW)
        proposal.proposal_id = i + 1
        sessions.append(ConsensusSession._new(proposal, config, NOW))
        n_arr[i] = n
        req[i] = required_votes_np(np.array([n]), threshold)[0]
        cap[i] = config.max_round_limit(n)
        gossip[i] = config.use_gossipsub_rounds
        liveness[i] = live
        expiry[i] = NOW + exp_off

    return (
        dict(
            state=state,
            yes=yes,
            tot=tot,
            vote_mask=vote_mask,
            vote_val=vote_val,
            n=n_arr,
            req=req,
            cap=cap,
            gossip=gossip,
            liveness=liveness,
            expiry=expiry,
        ),
        sessions,
    )


def run_ingest(pool, slots, voters, vals, now, kernel=None, voter_capacity=None):
    """Group the flat batch, run the kernel, return per-vote statuses in
    batch order plus updated numpy pool arrays. ``voter_capacity`` selects
    the narrow packed-grid dtype (uint8/uint16), as the pool does."""
    slots = np.asarray(slots, np.int64)
    uniq, row, col, depth = group_batch(slots)
    s_count = len(uniq)
    voter_grid = np.zeros((s_count, depth), np.int32)
    val_grid = np.zeros((s_count, depth), bool)
    valid_grid = np.zeros((s_count, depth), bool)
    voter_grid[row, col] = voters
    val_grid[row, col] = vals
    valid_grid[row, col] = True
    expired = (expiry_of(pool, uniq) <= now)

    out = (kernel or ingest_kernel)(
        jnp.asarray(pool["state"]),
        jnp.asarray(pool["yes"]),
        jnp.asarray(pool["tot"]),
        jnp.asarray(pool["vote_mask"]),
        jnp.asarray(pool["vote_val"]),
        jnp.asarray(pool["n"]),
        jnp.asarray(pool["req"]),
        jnp.asarray(pool["cap"]),
        jnp.asarray(pool["gossip"]),
        jnp.asarray(pool["liveness"]),
        jnp.asarray(pack_slots(uniq.astype(np.int32), expired)),
        jnp.asarray(
            pack_grid(
                voter_grid, val_grid, valid_grid, voter_capacity=voter_capacity
            )
        ),
    )
    state, yes, tot, vote_mask, vote_val, packed_out = map(np.asarray, out)
    pool.update(state=state, yes=yes, tot=tot, vote_mask=vote_mask, vote_val=vote_val)
    statuses = packed_out[:, :-1]
    return statuses[row, col]


def expiry_of(pool, uniq):
    return pool["expiry"][uniq]


class TestIngestParity:
    def _compare(self, pool, sessions, trace, now=NOW):
        slots = np.array([t[0] for t in trace])
        voters = np.array([t[1] for t in trace], np.int32)
        vals = np.array([t[2] for t in trace], bool)

        device_statuses = run_ingest(pool, slots, voters, vals, now)
        for b, (slot, voter, val) in enumerate(trace):
            expected = apply_scalar(sessions[slot], int(voter), bool(val), now)
            assert device_statuses[b] == expected, (
                f"vote {b} (slot={slot} voter={voter} val={val}): "
                f"device={StatusCode(device_statuses[b]).name} "
                f"oracle={StatusCode(expected).name}"
            )

        # Final states + tallies must agree.
        for i, session in enumerate(sessions):
            assert pool["state"][i] == scalar_state_code(session), f"slot {i} state"
            assert pool["tot"][i] == len(session.votes), f"slot {i} total"
            yes_scalar = sum(1 for v in session.votes.values() if v.vote)
            assert pool["yes"][i] == yes_scalar, f"slot {i} yes"
            for voter_idx in range(V_CAP):
                owner = bytes([voter_idx + 1])
                assert pool["vote_mask"][i, voter_idx] == (owner in session.votes)
                if owner in session.votes:
                    assert pool["vote_val"][i, voter_idx] == session.votes[owner].vote

    def test_basic_consensus_cut_midbatch(self):
        # n=3 gossipsub: third YES is a no-op (consensus after 2nd).
        pool, sessions = make_pool([(3, "gossipsub", True, 2 / 3, 1000)])
        self._compare(
            pool, sessions, [(0, 0, True), (0, 1, True), (0, 2, True)]
        )
        assert pool["state"][0] == STATE_REACHED_YES
        assert pool["tot"][0] == 2  # third vote was not inserted

    def test_duplicate_voters(self):
        pool, sessions = make_pool([(5, "gossipsub", True, 2 / 3, 1000)])
        self._compare(
            pool,
            sessions,
            [(0, 0, True), (0, 0, False), (0, 1, False), (0, 1, False)],
        )

    def test_p2p_round_cap_fails_session_midbatch(self):
        # n=4 p2p: cap=3; 4th vote exceeds -> Failed; 5th gets SessionNotActive.
        pool, sessions = make_pool([(4, "p2p", False, 2 / 3, 1000)])
        self._compare(
            pool,
            sessions,
            [(0, 0, True), (0, 1, False), (0, 2, True), (0, 3, True), (0, 4, True)],
        )
        assert pool["state"][0] == STATE_FAILED

    def test_expired_slot(self):
        pool, sessions = make_pool([(3, "gossipsub", True, 2 / 3, 10)])
        slots = np.array([0])
        voters = np.array([0], np.int32)
        vals = np.array([True])
        statuses = run_ingest(pool, slots, voters, vals, NOW + 10)
        assert statuses[0] == int(StatusCode.PROPOSAL_EXPIRED)
        expected = apply_scalar(sessions[0], 0, True, NOW + 10)
        assert statuses[0] == expected

    def test_cap_violation_beats_duplicate(self):
        # Precedence: round-cap check fires before the duplicate check
        # (reference: src/session.rs:232-239).
        pool, sessions = make_pool([(4, "p2p", False, 2 / 3, 1000)])
        self._compare(
            pool,
            sessions,
            [(0, 0, True), (0, 1, False), (0, 2, True), (0, 0, True)],
        )

    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_trace_parity(self, seed):
        rng = np.random.default_rng(seed)
        configs = []
        for _ in range(12):
            n = int(rng.integers(1, 13))
            mode = "gossipsub" if rng.random() < 0.5 else "p2p"
            live = bool(rng.random() < 0.5)
            threshold = float(rng.choice([2 / 3, 0.5, 0.9, 1.0]))
            exp_off = int(rng.choice([5, 1000]))  # some expire under test `now`
            configs.append((n, mode, live, threshold, exp_off))
        pool, sessions = make_pool(configs)

        trace = []
        for _ in range(150):
            slot = int(rng.integers(0, len(configs)))
            voter = int(rng.integers(0, V_CAP))
            val = bool(rng.random() < 0.5)
            trace.append((slot, voter, val))

        self._compare(pool, sessions, trace, now=NOW + 6)

    def test_fresh_kernel_cases(self):
        """Targeted fresh-kernel vs scan-kernel parity: the closed-form
        kernel must be bit-identical on its precondition domain (fresh
        ACTIVE slots, no duplicate voters): mid-batch decide cut, P2P
        round-cap fail, gossip cap, expired, no-terminal."""
        cases = [
            # (configs, trace)
            (
                [(3, "gossipsub", True, 2 / 3, 1000)],
                [(0, 0, True), (0, 1, True), (0, 2, True)],  # decide cut
            ),
            (
                [(4, "p2p", False, 2 / 3, 1000)],
                [(0, 0, True), (0, 1, False), (0, 2, True), (0, 3, True), (0, 4, True)],
            ),  # cap fail mid-batch then SESSION_NOT_ACTIVE
            (
                [(3, "gossipsub", True, 2 / 3, 10)],
                [(0, 0, True), (0, 1, False)],  # expired
            ),
            (
                [(8, "p2p", True, 0.9, 1000)],
                [(0, 0, True), (0, 1, False), (0, 2, True)],  # no terminal
            ),
            (
                [(6, "p2p", False, 1.0, 1000), (2, "gossipsub", True, 2 / 3, 1000)],
                [(0, 0, True), (1, 0, True), (0, 1, True), (1, 1, False),
                 (0, 2, False), (0, 3, True), (0, 4, True), (0, 5, True)],
            ),  # interleaved slots, unanimity n=2, threshold 1.0
        ]
        for configs, trace in cases:
            self._compare_fresh(configs, trace, now=NOW + 20)

    @pytest.mark.parametrize("seed", range(8))
    def test_fresh_kernel_randomized_parity(self, seed):
        """Randomized fresh traces (unique voters per slot — the fast-path
        precondition): statuses AND final pool arrays must match the scan
        kernel exactly."""
        rng = np.random.default_rng(1000 + seed)
        configs = []
        for _ in range(10):
            n = int(rng.integers(1, 13))
            mode = "gossipsub" if rng.random() < 0.5 else "p2p"
            live = bool(rng.random() < 0.5)
            threshold = float(rng.choice([2 / 3, 0.5, 0.9, 1.0]))
            exp_off = int(rng.choice([5, 1000]))
            configs.append((n, mode, live, threshold, exp_off))
        trace = []
        for slot in range(len(configs)):
            k = int(rng.integers(0, V_CAP + 1))
            voters = rng.permutation(V_CAP)[:k]  # unique per slot
            for v in voters:
                trace.append((slot, int(v), bool(rng.random() < 0.5)))
        rng.shuffle(trace)
        if not trace:
            trace = [(0, 0, True)]
        self._compare_fresh(configs, trace, now=NOW + 6)

    def _compare_fresh(self, configs, trace, now):
        from hashgraph_tpu.ops.ingest import fresh_ingest_kernel

        pool_scan, _ = make_pool(configs)
        pool_fresh, _ = make_pool(configs)
        slots = np.array([t[0] for t in trace])
        voters = np.array([t[1] for t in trace], np.int32)
        vals = np.array([t[2] for t in trace], bool)
        st_scan = run_ingest(pool_scan, slots, voters, vals, now)
        st_fresh = run_ingest(
            pool_fresh, slots, voters, vals, now, kernel=fresh_ingest_kernel
        )
        assert st_scan.tolist() == st_fresh.tolist(), (
            [StatusCode(s).name for s in st_scan],
            [StatusCode(s).name for s in st_fresh],
        )
        for key in ("state", "yes", "tot", "vote_mask", "vote_val"):
            assert (pool_scan[key] == pool_fresh[key]).all(), key

    @pytest.mark.parametrize("seed", range(4))
    def test_fresh_laneless_parity(self, seed):
        """The laneless fresh kernel (value/valid-only uint8 grid, lanes
        reconstructed on device as the within-slot arrival index) must be
        bit-identical to the lane-ful fresh kernel when lanes == col —
        the exact precondition >64-lane pools enforce before using it."""
        from hashgraph_tpu.ops.ingest import (
            fresh_ingest_kernel,
            fresh_ingest_laneless_kernel,
            group_batch,
            pack_slots,
        )

        rng = np.random.default_rng(7100 + seed)
        configs = []
        for _ in range(8):
            n = int(rng.integers(1, 13))
            mode = "gossipsub" if rng.random() < 0.5 else "p2p"
            configs.append(
                (n, mode, bool(rng.random() < 0.5),
                 float(rng.choice([2 / 3, 1.0])), int(rng.choice([5, 1000])))
            )
        trace = []
        for slot in range(len(configs)):
            for _ in range(int(rng.integers(0, V_CAP + 1))):
                trace.append((slot, bool(rng.random() < 0.5)))
        rng.shuffle(trace)
        if not trace:
            trace = [(0, True)]
        slots = np.array([t[0] for t in trace])
        vals = np.array([t[1] for t in trace], bool)
        s_arr = np.asarray(slots, np.int64)
        uniq, row, col, depth = group_batch(s_arr)
        # Lanes = within-slot arrival index: the fresh assignment rule,
        # and the laneless kernel's reconstruction.
        voters = col.astype(np.int32)

        pool_l, _ = make_pool(configs)
        st_lane = run_ingest(
            pool_l, slots, voters, vals, NOW + 6, kernel=fresh_ingest_kernel
        )
        # Laneless: same grouping, but the grid carries value|valid only.
        pool_n, _ = make_pool(configs)
        grid = np.zeros((len(uniq), depth), np.uint8)
        grid[row, col] = vals.astype(np.uint8) | 2
        import jax.numpy as jnp

        out = fresh_ingest_laneless_kernel(
            jnp.asarray(pool_n["state"]),
            jnp.asarray(pool_n["yes"]),
            jnp.asarray(pool_n["tot"]),
            jnp.asarray(pool_n["vote_mask"]),
            jnp.asarray(pool_n["vote_val"]),
            jnp.asarray(pool_n["n"]),
            jnp.asarray(pool_n["req"]),
            jnp.asarray(pool_n["cap"]),
            jnp.asarray(pool_n["gossip"]),
            jnp.asarray(pool_n["liveness"]),
            jnp.asarray(
                pack_slots(
                    uniq.astype(np.int32),
                    pool_n["expiry"][uniq] <= NOW + 6,
                )
            ),
            jnp.asarray(grid),
        )
        state, yes, tot, vote_mask, vote_val, packed = map(np.asarray, out)
        pool_n.update(
            state=state, yes=yes, tot=tot,
            vote_mask=vote_mask, vote_val=vote_val,
        )
        st_laneless = packed[:, :-1][row, col]
        assert st_lane.tolist() == st_laneless.tolist()
        for key in ("state", "yes", "tot", "vote_mask", "vote_val"):
            assert (pool_l[key] == pool_n[key]).all(), key

    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("cap_hint", [16, 4096, None])
    def test_grid_dtype_parity(self, seed, cap_hint):
        """Narrow packed grids (uint8 for capacity<=64, uint16 for <=16384)
        must be bit-identical to the int32 layout on BOTH kernels — the
        dtype only changes the transfer width, never the unpacked lanes."""
        from hashgraph_tpu.ops.ingest import fresh_ingest_kernel, grid_dtype

        if cap_hint is not None:
            expect = np.uint8 if cap_hint <= 64 else np.uint16
            assert grid_dtype(cap_hint) == expect
        rng = np.random.default_rng(4200 + seed)
        configs = []
        for _ in range(6):
            n = int(rng.integers(1, 13))
            mode = "gossipsub" if rng.random() < 0.5 else "p2p"
            configs.append(
                (n, mode, bool(rng.random() < 0.5),
                 float(rng.choice([2 / 3, 0.9])), int(rng.choice([5, 1000])))
            )
        trace = []
        for slot in range(len(configs)):
            for v in rng.permutation(V_CAP)[: int(rng.integers(1, V_CAP))]:
                trace.append((slot, int(v), bool(rng.random() < 0.5)))
        rng.shuffle(trace)
        slots = np.array([t[0] for t in trace])
        voters = np.array([t[1] for t in trace], np.int32)
        vals = np.array([t[2] for t in trace], bool)
        for kernel in (None, fresh_ingest_kernel):
            pool_ref, _ = make_pool(configs)
            pool_nar, _ = make_pool(configs)
            st_ref = run_ingest(
                pool_ref, slots, voters, vals, NOW + 6, kernel=kernel
            )
            st_nar = run_ingest(
                pool_nar, slots, voters, vals, NOW + 6, kernel=kernel,
                voter_capacity=cap_hint,
            )
            assert st_ref.tolist() == st_nar.tolist()
            for key in ("state", "yes", "tot", "vote_mask", "vote_val"):
                assert (pool_ref[key] == pool_nar[key]).all(), key

    def test_pad_rows_cannot_corrupt_pool(self):
        pool, sessions = make_pool([(3, "gossipsub", True, 2 / 3, 1000)])
        p_count = len(sessions)
        # One real row + one pad row with slot_id == P (sentinel).
        out = ingest_kernel(
            jnp.asarray(pool["state"]),
            jnp.asarray(pool["yes"]),
            jnp.asarray(pool["tot"]),
            jnp.asarray(pool["vote_mask"]),
            jnp.asarray(pool["vote_val"]),
            jnp.asarray(pool["n"]),
            jnp.asarray(pool["req"]),
            jnp.asarray(pool["cap"]),
            jnp.asarray(pool["gossip"]),
            jnp.asarray(pool["liveness"]),
            jnp.asarray(
                pack_slots(np.array([0, p_count], np.int32), np.array([False, False]))
            ),
            jnp.asarray(
                pack_grid(
                    np.array([[0], [0]], np.int32),
                    np.array([[True], [True]]),
                    np.array([[True], [False]]),  # pad row: all cells invalid
                )
            ),
        )
        state, yes, tot, mask, vals, packed_out = map(np.asarray, out)
        statuses = packed_out[:, :-1]
        assert statuses[0, 0] == int(StatusCode.OK)
        assert statuses[1, 0] == PAD_STATUS
        assert tot[0] == 1 and yes[0] == 1
