"""Columnar (structure-of-arrays) engine paths: create_proposals and
ingest_columnar must be observably equivalent to their scalar counterparts.

The columnar path is the framework's throughput surface (BASELINE north
star: >=1M vote-ingests/sec at the service level); these tests pin its
semantics to the per-vote path — statuses, final states, event counts,
duplicate/capacity/unknown handling — on randomized traces."""

import numpy as np
import pytest

from hashgraph_tpu import CreateProposalRequest, StatusCode, build_vote
from hashgraph_tpu.engine import TpuConsensusEngine

from common import NOW, random_stub_signer


def request(n=4, name="p", exp=1000, liveness=True):
    return CreateProposalRequest(
        name=name,
        payload=b"x",
        proposal_owner=b"o",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


def make_engine(**kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("voter_capacity", 8)
    kw.setdefault("max_sessions_per_scope", 1000)
    return TpuConsensusEngine(random_stub_signer(), **kw)


def drain(receiver):
    events = []
    while (item := receiver.try_recv()) is not None:
        events.append(item)
    return events


class TestCreateProposalsBatch:
    def test_equivalent_to_scalar_loop(self):
        batch_engine = make_engine()
        scalar_engine = make_engine()
        reqs = [request(n=3 + (i % 4), name=f"p{i}") for i in range(10)]
        batch_proposals = batch_engine.create_proposals("s", reqs, NOW)
        scalar_proposals = [
            scalar_engine.create_proposal("s", r, NOW) for r in reqs
        ]
        assert len(batch_proposals) == 10
        assert batch_engine.get_scope_stats("s").total_sessions == 10
        for bp, sp in zip(batch_proposals, scalar_proposals):
            assert bp.expected_voters_count == sp.expected_voters_count
            assert bp.round == sp.round == 1
            # Same resolved config on both engines' records.
            b_rec = batch_engine._records[batch_engine._index[("s", bp.proposal_id)]]
            s_rec = scalar_engine._records[scalar_engine._index[("s", sp.proposal_id)]]
            assert b_rec.config == s_rec.config

    def test_batch_with_spills(self):
        engine = make_engine(capacity=4, voter_capacity=4)
        # 6 requests into a 4-slot pool, one oversized: 3 pooled + spills.
        reqs = [request(n=4, name=f"p{i}") for i in range(5)] + [
            request(n=100, name="big")
        ]
        proposals = engine.create_proposals("s", reqs, NOW)
        assert len(proposals) == 6
        assert engine.get_scope_stats("s").total_sessions == 6
        assert engine.pool().allocated_slots == 4
        # The oversized one runs host-backed and still takes votes.
        big = proposals[-1]
        vote = build_vote(
            engine.get_proposal("s", big.proposal_id), True, random_stub_signer(), NOW
        )
        assert engine.ingest_votes([("s", vote)], NOW)[0] == int(StatusCode.OK)

    def test_batch_respects_scope_cap(self):
        engine = make_engine(max_sessions_per_scope=3)
        proposals = engine.create_proposals(
            "s", [request(name=f"p{i}") for i in range(5)], NOW + 1
        )
        assert len(proposals) == 5
        assert engine.get_scope_stats("s").total_sessions == 3

    def test_p2p_cap_matches_scalar(self):
        from hashgraph_tpu.scope_config import NetworkType

        engine = make_engine()
        engine.scope("s").with_network_type(NetworkType.P2P).initialize()
        [p] = engine.create_proposals("s", [request(n=6)], NOW)
        slot = engine._index[("s", p.proposal_id)]
        scalar_engine = make_engine()
        scalar_engine.scope("s").with_network_type(NetworkType.P2P).initialize()
        sp = scalar_engine.create_proposal("s", request(n=6), NOW)
        s_slot = scalar_engine._index[("s", sp.proposal_id)]
        b_cap = int(np.asarray(engine.pool()._cap)[slot])
        s_cap = int(np.asarray(scalar_engine.pool()._cap)[s_slot])
        assert b_cap == s_cap == 4  # ceil(2*6/3)


class TestColumnarIngestParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_grouped_vs_shuffled_arrival_parity(self, seed):
        """A proposal-major (grouped) batch takes the sort-skipping fast
        path; a cross-proposal shuffle of the same trace takes the argsort
        path. Per-proposal outcomes must be identical — the grouped
        detection has to be semantically invisible."""
        rng = np.random.default_rng(900 + seed)

        def run(shuffle: bool):
            eng = make_engine(capacity=64)
            ps = eng.create_proposals(
                "s",
                [request(n=6, name=f"p{i}", liveness=bool(i % 2))
                 for i in range(24)],
                NOW,
            )
            gids = [eng.voter_gid(bytes([20 + i]) * 20) for i in range(6)]
            rows = []
            for k, p in enumerate(ps):
                for v in range(4):
                    rows.append((p.proposal_id, gids[v], bool((k + v) % 3)))
            if shuffle:
                # Full row shuffle breaks the grouped property. Outcomes
                # stay order-independent at this shape: required votes =
                # 4 of 6, and each proposal gets exactly 4 distinct
                # voters, so the decision always lands on the 4th vote.
                idx = rng.permutation(len(rows))
                rows = [rows[i] for i in idx]
            eng.ingest_columnar(
                "s",
                np.array([r[0] for r in rows], np.int64),
                np.array([r[1] for r in rows], np.int64),
                np.array([r[2] for r in rows], bool),
                NOW + 1,
            )
            out = []
            for p in ps:
                try:
                    out.append(eng.get_consensus_result("s", p.proposal_id))
                except Exception as exc:
                    out.append(type(exc).__name__)
            return out

        assert run(False) == run(True)

    @pytest.mark.parametrize("seed", range(3))
    def test_random_trace_parity_with_ingest_votes(self, seed):
        rng = np.random.default_rng(seed)
        n_props, n_voters = 6, 6
        col_engine = make_engine()
        vote_engine = make_engine()
        col_engine.scope("s").with_threshold(1.0).initialize()
        vote_engine.scope("s").with_threshold(1.0).initialize()
        reqs = [request(n=n_voters, name=f"p{i}") for i in range(n_props)]
        col_pids = [p.proposal_id for p in col_engine.create_proposals("s", reqs, NOW)]
        vote_pids = [p.proposal_id for p in vote_engine.create_proposals("s", reqs, NOW)]

        owners = [bytes([10 + i]) * 20 for i in range(n_voters)]
        gids = [col_engine.voter_gid(o) for o in owners]
        col_rx = col_engine.event_bus().subscribe()
        vote_rx = vote_engine.event_bus().subscribe()

        # Random arrival-ordered trace with duplicates sprinkled in.
        trace = []  # (prop_idx, voter_idx, value)
        for _ in range(n_props * n_voters + 10):
            trace.append(
                (
                    int(rng.integers(n_props)),
                    int(rng.integers(n_voters)),
                    bool(rng.random() < 0.5),
                )
            )

        from hashgraph_tpu.wire import Vote

        col_statuses = col_engine.ingest_columnar(
            "s",
            np.array([col_pids[p] for p, _, _ in trace], np.int64),
            np.array([gids[v] for _, v, _ in trace], np.int64),
            np.array([val for _, _, val in trace], bool),
            NOW,
            max_depth=3,  # force multi-segment
        )
        vote_items = [
            (
                "s",
                Vote(
                    vote_id=1,
                    vote_owner=owners[v],
                    proposal_id=vote_pids[p],
                    timestamp=NOW,
                    vote=val,
                    parent_hash=b"",
                    received_hash=b"",
                    vote_hash=b"h",
                    signature=b"s",
                ),
            )
            for p, v, val in trace
        ]
        vote_statuses = vote_engine.ingest_votes(vote_items, NOW, pre_validated=True)

        assert list(col_statuses) == list(vote_statuses)
        for cp, vp in zip(col_pids, vote_pids):
            c_state = col_engine._state_code(
                col_engine._records[col_engine._index[("s", cp)]]
            )
            v_state = vote_engine._state_code(
                vote_engine._records[vote_engine._index[("s", vp)]]
            )
            assert c_state == v_state
            # Round bookkeeping parity.
            assert (
                col_engine.get_proposal("s", cp).round
                == vote_engine.get_proposal("s", vp).round
            )
        # Event parity: same multiset of (pid-index, result) with same counts.
        col_events = sorted(
            (col_pids.index(e.proposal_id), e.result) for _, e in drain(col_rx)
        )
        vote_events = sorted(
            (vote_pids.index(e.proposal_id), e.result) for _, e in drain(vote_rx)
        )
        assert col_events == vote_events

    def test_unknown_pid_and_capacity(self):
        engine = make_engine(voter_capacity=2)
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        # n=8 > 2 lanes: spilled to host; columnar falls back per vote.
        gid = engine.voter_gid(b"\x01" * 20)
        st = engine.ingest_columnar(
            "s",
            np.array([p.proposal_id, 999_999_999], np.int64),
            np.array([gid, gid], np.int64),
            np.array([True, True], bool),
            NOW,
        )
        assert st[0] == int(StatusCode.OK)  # host-backed fallback accepted
        assert st[1] == int(StatusCode.SESSION_NOT_FOUND)

    def test_lane_capacity_exceeded_columnar(self):
        engine = make_engine(capacity=4, voter_capacity=2)
        engine.scope("s").with_threshold(1.0).initialize()
        [p] = engine.create_proposals("s", [request(n=2, name="x")], NOW)
        gids = np.array(
            [engine.voter_gid(bytes([i]) * 20) for i in range(1, 4)], np.int64
        )
        st = engine.ingest_columnar(
            "s",
            np.full(3, p.proposal_id, np.int64),
            gids,
            np.array([True, False, True], bool),
            NOW,
        )
        # Two lanes assigned; the third distinct owner exhausts capacity.
        assert list(st[:2]) == [int(StatusCode.OK)] * 2
        assert st[2] == int(StatusCode.VOTER_CAPACITY_EXCEEDED)

    def test_already_reached_reemission_counts(self):
        engine = make_engine()
        [p] = engine.create_proposals("s", [request(n=2, name="x")], NOW)
        rx = engine.event_bus().subscribe()
        gids = np.array(
            [engine.voter_gid(bytes([i]) * 20) for i in range(1, 5)], np.int64
        )
        st = engine.ingest_columnar(
            "s",
            np.full(4, p.proposal_id, np.int64),
            gids,
            np.ones(4, bool),
            NOW,
            max_depth=1,
        )
        # n=2 unanimity: decided on vote 2; votes 3-4 are late.
        assert list(st) == [
            int(StatusCode.OK),
            int(StatusCode.OK),
            int(StatusCode.ALREADY_REACHED),
            int(StatusCode.ALREADY_REACHED),
        ]
        events = drain(rx)
        assert len(events) == 3  # deciding emit + 2 re-emits
        assert all(e.result is True for _, e in events)


class TestColumnarSpillIntegrity:
    """Advisor r2 medium: the columnar spill path must not fabricate
    unsigned Vote objects — a peer replaying the exported proposal would
    reject the whole chain."""

    def test_spilled_columnar_votes_are_tally_only(self):
        engine = make_engine(voter_capacity=2)
        # n=8 > 2 lanes: host-spilled.
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        owners = [bytes([10 + i]) * 20 for i in range(6)]
        gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        st = engine.ingest_columnar(
            "s",
            np.full(6, p.proposal_id, np.int64),
            gids,
            np.ones(6, bool),
            NOW,
        )
        assert list(st) == [int(StatusCode.OK)] * 6
        # No synthetic Vote objects anywhere observable.
        exported = engine.export_session("s", p.proposal_id)
        assert exported.proposal.votes == []
        assert exported.votes == {}
        assert dict(exported.tallies) == {o: True for o in owners}
        assert engine.get_proposal("s", p.proposal_id).votes == []
        # The tallies counted: 6 yes + 2 liveness-yes silents clears the
        # ceil(2*8/3)=6 bar, so the session decided on the tallies alone.
        assert engine.get_consensus_result("s", p.proposal_id) is True
        more = [bytes([30 + i]) * 20 for i in range(2)]
        st2 = engine.ingest_columnar(
            "s",
            np.full(2, p.proposal_id, np.int64),
            np.array([engine.voter_gid(o) for o in more], np.int64),
            np.ones(2, bool),
            NOW,
        )
        assert list(st2) == [int(StatusCode.ALREADY_REACHED)] * 2

    def test_spilled_exported_proposal_regossips_cleanly(self):
        """A proposal exported after columnar spill ingest must pass a peer
        engine's full validation gauntlet (empty chain == valid chain)."""
        engine = make_engine(voter_capacity=2)
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        gid = engine.voter_gid(b"\x07" * 20)
        engine.ingest_columnar(
            "s",
            np.array([p.proposal_id], np.int64),
            np.array([gid], np.int64),
            np.array([True], bool),
            NOW,
        )
        exported = engine.get_proposal("s", p.proposal_id)
        peer = make_engine()
        peer.process_incoming_proposal("s", exported, NOW)  # must not raise

    def test_columnar_tally_and_scalar_vote_dedup_each_other(self):
        engine = make_engine(voter_capacity=2)
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        owner = b"\x09" * 20
        gid = engine.voter_gid(owner)
        st = engine.ingest_columnar(
            "s",
            np.array([p.proposal_id], np.int64),
            np.array([gid], np.int64),
            np.array([True], bool),
            NOW,
        )
        assert st[0] == int(StatusCode.OK)
        # The same owner voting through the scalar path is a duplicate.
        from hashgraph_tpu.wire import Vote

        vote = Vote(
            vote_id=1,
            vote_owner=owner,
            proposal_id=p.proposal_id,
            timestamp=NOW,
            vote=True,
            parent_hash=b"",
            received_hash=b"",
            vote_hash=b"h",
            signature=b"s",
        )
        st2 = engine.ingest_votes([("s", vote)], NOW, pre_validated=True)
        assert st2[0] == int(StatusCode.DUPLICATE_VOTE)

    def test_uninterned_gid_typed_status_both_substrates(self):
        """Advisor r2 low: an un-interned gid must produce a per-row typed
        status, not an IndexError (spill) or a silent fresh voter (device)."""
        engine = make_engine(voter_capacity=2)
        pooled, spilled = engine.create_proposals(
            "s", [request(n=2, name="a"), request(n=8, name="b")], NOW
        )
        good = engine.voter_gid(b"\x01" * 20)
        st = engine.ingest_columnar(
            "s",
            np.array(
                [pooled.proposal_id, spilled.proposal_id] * 2, np.int64
            ),
            np.array([good, good, 999, -1], np.int64),
            np.ones(4, bool),
            NOW,
        )
        assert list(st[:2]) == [int(StatusCode.OK)] * 2
        assert list(st[2:]) == [int(StatusCode.EMPTY_VOTE_OWNER)] * 2

    def test_cast_vote_after_own_columnar_tally_raises_user_already_voted(self):
        from hashgraph_tpu import UserAlreadyVoted

        engine = make_engine(voter_capacity=2)
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        gid = engine.voter_gid(engine.signer().identity())
        st = engine.ingest_columnar(
            "s",
            np.array([p.proposal_id], np.int64),
            np.array([gid], np.int64),
            np.array([True], bool),
            NOW,
        )
        assert st[0] == int(StatusCode.OK)
        with pytest.raises(UserAlreadyVoted):
            engine.cast_vote("s", p.proposal_id, True, NOW)

    def test_checkpoint_roundtrip_preserves_tallies(self):
        from hashgraph_tpu import InMemoryConsensusStorage

        engine = make_engine(voter_capacity=2)
        [p] = engine.create_proposals("s", [request(n=8, name="x")], NOW)
        owners = [bytes([40 + i]) * 20 for i in range(3)]
        engine.ingest_columnar(
            "s",
            np.full(3, p.proposal_id, np.int64),
            np.array([engine.voter_gid(o) for o in owners], np.int64),
            np.array([True, False, True], bool),
            NOW,
        )
        storage = InMemoryConsensusStorage()
        engine.save_to_storage(storage)
        restored = make_engine(voter_capacity=2)
        restored.load_from_storage(storage)
        session = restored.export_session("s", p.proposal_id)
        assert dict(session.tallies) == {
            owners[0]: True,
            owners[1]: False,
            owners[2]: True,
        }


class TestPidLookup:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_hash_lookup_matches_dict_oracle(self, seed):
        """Property: for random u32 pid sets, _PidLookup.lookup agrees with
        a plain dict on hits, misses, near-misses, and sentinel values."""
        from hashgraph_tpu.engine.engine import _PidLookup

        rng = np.random.default_rng(seed)
        n = int(rng.integers(1, 3000))
        pids = rng.choice(2**32 - 1, size=n, replace=False).astype(np.int64)
        slots = rng.integers(0, 10_000, size=n).astype(np.int64)
        table = _PidLookup(pids, slots)
        oracle = dict(zip(pids.tolist(), slots.tolist()))
        queries = np.concatenate(
            [
                pids[rng.integers(0, n, size=500)],  # hits
                rng.choice(2**32 - 1, size=500).astype(np.int64),  # mostly miss
                np.array([-1, 0, 2**32 - 1, 2**63 - 1, -(2**62)], np.int64),
            ]
        )
        found, out = table.lookup(queries)
        for q, f, s in zip(queries.tolist(), found.tolist(), out.tolist()):
            assert f == (q in oracle), q
            if f:
                assert s == oracle[q], q

    def test_empty_table(self):
        from hashgraph_tpu.engine.engine import _PidLookup

        table = _PidLookup(np.empty(0, np.int64), np.empty(0, np.int64))
        found, out = table.lookup(np.array([0, 1, -1], np.int64))
        assert not found.any()

    @pytest.mark.parametrize("seed", [0, 1])
    def test_native_probe_matches_numpy_probe(self, seed, monkeypatch):
        """lookup() auto-routes big batches to the native fused probe when
        the runtime is present; its results must be bit-identical to the
        numpy probe loop on the same table and queries (hits, misses,
        negative junk, the -1 sentinel, and negative slot values)."""
        from hashgraph_tpu import native
        from hashgraph_tpu.engine.engine import _PidLookup

        if not native.available():
            pytest.skip("native runtime absent: nothing to compare")
        rng = np.random.default_rng(40 + seed)
        n = int(rng.integers(1, 4000))
        pids = rng.choice(2**32 - 1, size=n, replace=False).astype(np.int64)
        slots = rng.integers(-50, 10_000, size=n).astype(np.int64)  # spills < 0
        table = _PidLookup(pids, slots)
        queries = np.concatenate(
            [
                pids[rng.integers(0, n, size=700)],
                rng.integers(-(2**40), 2**40, size=700),
                np.array([-1, 0, 2**63 - 1], np.int64),
            ]
        )
        res_auto = table.lookup(queries)  # native when available
        monkeypatch.setattr(native, "pid_lookup", lambda *a, **k: None)
        res_np = table.lookup(queries)  # forced numpy fallback
        assert (res_auto[0] == res_np[0]).all()
        assert (res_auto[1] == res_np[1]).all()


class TestMultiScopeColumnar:
    def test_multi_scope_parity_with_per_scope_calls(self):
        """ingest_columnar_multi over N scopes must produce exactly the
        per-row statuses and final states of N separate single-scope calls
        on an identically-prepared engine."""
        rng = np.random.default_rng(5)
        scopes = [f"sc{i}" for i in range(6)]
        owners = [bytes([60 + v]) * 20 for v in range(4)]

        def build(engine):
            # Intern voters first, identical order: both engines' fresh
            # registries then assign identical gids.
            for owner in owners:
                engine.voter_gid(owner)
            pids = {}
            for scope in scopes:
                proposals = engine.create_proposals(
                    scope, [request(n=4) for _ in range(8)], NOW
                )
                pids[scope] = [p.proposal_id for p in proposals]
            return pids

        def vote_columns(engine, pids):
            rows = []
            for k, scope in enumerate(scopes):
                for pid in pids[scope]:
                    for v in range(3):
                        rows.append(
                            (k, pid, engine.voter_gid(owners[v]),
                             bool(rng.integers(2)))
                        )
            order = rng.permutation(len(rows))
            rows = [rows[i] for i in order]
            return (
                np.array([r[0] for r in rows], np.int64),
                np.array([r[1] for r in rows], np.int64),
                np.array([r[2] for r in rows], np.int64),
                np.array([r[3] for r in rows], bool),
            )

        eng_multi = make_engine()
        pids_m = build(eng_multi)
        sidx, pid_col, gid_col, val_col = vote_columns(eng_multi, pids_m)
        multi_status = eng_multi.ingest_columnar_multi(
            scopes, sidx, pid_col, gid_col, val_col, NOW + 1
        )

        eng_single = make_engine()
        pids_s = build(eng_single)
        # Map multi pids -> single pids positionally per scope.
        remap = {}
        for scope in scopes:
            for pm, ps in zip(pids_m[scope], pids_s[scope]):
                remap[pm] = ps
        single_status = np.empty_like(multi_status)
        for k, scope in enumerate(scopes):
            rows = np.nonzero(sidx == k)[0]
            single_status[rows] = eng_single.ingest_columnar(
                scope,
                np.array([remap[p] for p in pid_col[rows]], np.int64),
                gid_col[rows],
                val_col[rows],
                NOW + 1,
            )
        assert (multi_status == single_status).all()
        for k, scope in enumerate(scopes):
            for pm in pids_m[scope]:
                try:
                    rm = eng_multi.get_consensus_result(scope, pm)
                except Exception as exc:  # ConsensusFailed parity
                    rm = type(exc).__name__
                try:
                    rs = eng_single.get_consensus_result(scope, remap[pm])
                except Exception as exc:
                    rs = type(exc).__name__
                assert rm == rs, (scope, pm, rm, rs)

    def test_negative_pid_never_matches_hash_sentinel(self):
        """pid -1 must resolve to SESSION_NOT_FOUND, not alias the
        _PidLookup empty-bucket sentinel onto slot 0 (a -1 row once cast a
        vote into whatever session occupied slot 0, across scopes)."""
        engine = make_engine()
        [p] = engine.create_proposals("A", [request(n=4)], NOW)
        gid = engine.voter_gid(b"\x66" * 20)
        st = engine.ingest_columnar(
            "B", np.array([-1]), np.array([gid]), np.array([True]), NOW
        )
        assert st.tolist() == [int(StatusCode.SESSION_NOT_FOUND)]
        st = engine.ingest_columnar(
            "A",
            np.array([-1, p.proposal_id, 2**63 - 1]),
            np.array([gid] * 3),
            np.array([True] * 3),
            NOW,
        )
        assert st.tolist() == [
            int(StatusCode.SESSION_NOT_FOUND),
            int(StatusCode.OK),
            int(StatusCode.SESSION_NOT_FOUND),
        ]
        # Slot 0's session saw exactly the one legitimate vote.
        assert engine.get_scope_stats("A").total_sessions == 1

    def test_create_proposals_multi_matches_per_scope_loop(self):
        """One cross-scope allocate must register exactly what per-scope
        create_proposals calls would: same counts, same per-scope stats,
        same spill behavior when the pool runs out, and a rejected
        duplicate scope."""
        eng = make_engine(capacity=16)
        scopes = ["m0", "m1", "m2"]
        batches = eng.create_proposals_multi(
            [(s, [request(n=4) for _ in range(6)]) for s in scopes], NOW
        )
        assert [len(b) for b in batches] == [6, 6, 6]
        for scope, batch in zip(scopes, batches):
            stats = eng.get_scope_stats(scope)
            assert stats.total_sessions == 6 and stats.active_sessions == 6
            for p in batch:
                assert eng.get_consensus_result(scope, p.proposal_id) is None
        # 18 sessions > 16 slots: exactly 2 spilled to the host substrate.
        assert eng.pool().free_slots == 0
        spilled = sum(
            1 for r in eng._records.values() if r.session is not None
        )
        assert spilled == 2
        with pytest.raises(ValueError):
            eng.create_proposals_multi(
                [("dup", [request()]), ("dup", [request()])], NOW
            )

    def test_multi_scope_unknown_scope_and_pid(self):
        engine = make_engine()
        [p] = engine.create_proposals("known", [request(n=4)], NOW)
        gid = engine.voter_gid(b"\x77" * 20)
        statuses = engine.ingest_columnar_multi(
            ["known", "ghost"],
            np.array([0, 1, 0]),
            np.array([p.proposal_id, p.proposal_id, 999], np.int64),
            np.array([gid] * 3),
            np.ones(3, bool),
            NOW + 1,
        )
        assert statuses.tolist() == [
            int(StatusCode.OK),
            int(StatusCode.SESSION_NOT_FOUND),  # scope exists elsewhere only
            int(StatusCode.SESSION_NOT_FOUND),  # unknown pid
        ]

    def test_wide_pid_cannot_alias_fused_composite_key(self):
        """The fused multi-scope lookup keys on scope_ordinal << 32 | pid.
        A caller-supplied pid wider than u32 (e.g. (1 << 32) | real_pid)
        must resolve as not-found, never alias another scope's session."""
        engine = make_engine()
        [pa] = engine.create_proposals("a", [request(n=4)], NOW)
        [pb] = engine.create_proposals("b", [request(n=4)], NOW)
        gid = engine.voter_gid(b"\x66" * 20)
        wide = (np.int64(1) << 32) | np.int64(pb.proposal_id)
        statuses = engine.ingest_columnar_multi(
            ["a", "b"],
            np.array([0, 0, 1], np.int64),
            # Row 1's wide pid equals the composite key of scope b's
            # session — a missing u32 guard would misroute the vote.
            np.array([pa.proposal_id, wide, pb.proposal_id], np.int64),
            np.array([gid] * 3, np.int64),
            np.ones(3, bool),
            NOW + 1,
        )
        assert statuses.tolist() == [
            int(StatusCode.OK),
            int(StatusCode.SESSION_NOT_FOUND),
            int(StatusCode.OK),
        ]
        # The wide row must not have been credited to scope b's session:
        # exactly the one direct vote, not two.
        assert len(engine.export_session("b", pb.proposal_id).votes) <= 1
        assert engine.get_scope_stats("b").total_sessions == 1

    def test_fused_cache_invalidated_by_membership_change(self):
        """Delete + recreate between two multi calls: the second call must
        resolve the NEW sessions (epoch-keyed fused cache, not stale)."""
        engine = make_engine()
        scopes = ["x", "y"]
        gid = engine.voter_gid(b"\x55" * 20)
        first = {
            s: engine.create_proposals(s, [request(n=4)], NOW)[0]
            for s in scopes
        }
        st1 = engine.ingest_columnar_multi(
            scopes,
            np.array([0, 1], np.int64),
            np.array(
                [first["x"].proposal_id, first["y"].proposal_id], np.int64
            ),
            np.array([gid] * 2, np.int64),
            np.ones(2, bool),
            NOW + 1,
        )
        assert st1.tolist() == [int(StatusCode.OK)] * 2
        engine.delete_scope("x")
        [nx] = engine.create_proposals("x", [request(n=4)], NOW)
        gid2 = engine.voter_gid(b"\x54" * 20)
        st2 = engine.ingest_columnar_multi(
            scopes,
            np.array([0, 0, 1], np.int64),
            np.array(
                [
                    nx.proposal_id,
                    first["x"].proposal_id,  # deleted session
                    first["y"].proposal_id,
                ],
                np.int64,
            ),
            np.array([gid2] * 3, np.int64),
            np.ones(3, bool),
            NOW + 1,
        )
        assert st2.tolist() == [
            int(StatusCode.OK),
            int(StatusCode.SESSION_NOT_FOUND),
            int(StatusCode.OK),
        ]


class TestWireRetention:
    """Opt-in wire_votes retention closes the columnar chain gap: a proposal
    ingested columnar can be re-gossiped and chain-validates at a peer
    (reference: src/utils.rs:175-215, src/service.rs:216-237)."""

    def _chained_votes(self, proposal, signers, now):
        """Build a chain-linked vote list the way real peers would: each
        vote links to the proposal's current tail."""
        votes = []
        ferry = proposal.clone()
        for i, signer in enumerate(signers):
            vote = build_vote(ferry, True, signer, now + i)
            ferry.votes.append(vote)
            votes.append(vote)
        return votes

    def test_regossip_after_columnar_ingest_chain_validates_at_peer(self):
        engine_a = make_engine()
        engine_b = make_engine()
        # n=4 with liveness: the 3rd YES is the deciding vote, so all three
        # rows are accepted (OK) and retained.
        proposal = engine_a.create_proposal("s", request(n=4), NOW)
        signers = [random_stub_signer() for _ in range(3)]
        votes = self._chained_votes(proposal, signers, NOW + 1)

        gids = np.array([engine_a.voter_gid(v.vote_owner) for v in votes])
        statuses = engine_a.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=[v.encode() for v in votes],
        )
        assert (statuses == int(StatusCode.OK)).all(), statuses
        assert engine_a.get_consensus_result("s", proposal.proposal_id) is True

        # Re-gossip: the exported proposal embeds the verbatim signed votes
        # in arrival order; a second engine runs the FULL validation gauntlet
        # (signatures + hash chain) on it.
        exported = engine_a.get_proposal("s", proposal.proposal_id)
        assert len(exported.votes) == 3
        assert [v.vote_owner for v in exported.votes] == [
            v.vote_owner for v in votes
        ]
        wire = exported.encode()
        from hashgraph_tpu import Proposal

        engine_b.process_incoming_proposal("s", Proposal.decode(wire), NOW + 11)
        assert engine_b.get_consensus_result("s", proposal.proposal_id) is True

    def test_retention_skips_rejected_rows(self):
        engine = make_engine()
        proposal = engine.create_proposal("s", request(n=4), NOW)
        signers = [random_stub_signer() for _ in range(2)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        # Duplicate the second vote: the replay must be rejected AND not
        # retained (a retained duplicate would poison the exported chain).
        batch = votes + [votes[1]]
        gids = np.array([engine.voter_gid(v.vote_owner) for v in batch])
        statuses = engine.ingest_columnar(
            "s",
            np.full(len(batch), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in batch]),
            NOW + 10,
            wire_votes=[v.encode() for v in batch],
        )
        assert statuses.tolist()[:2] == [int(StatusCode.OK)] * 2
        assert statuses[2] == int(StatusCode.DUPLICATE_VOTE)
        exported = engine.get_proposal("s", proposal.proposal_id)
        assert len(exported.votes) == 2

    def test_multi_batch_retention_preserves_arrival_order(self):
        engine = make_engine()
        # n=5, liveness NO: the 4th YES decides (required=4), so all four
        # rows across the two batches are accepted and retained.
        proposal = engine.create_proposal("s", request(n=5, liveness=False), NOW)
        signers = [random_stub_signer() for _ in range(4)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        for half in (votes[:2], votes[2:]):
            gids = np.array([engine.voter_gid(v.vote_owner) for v in half])
            statuses = engine.ingest_columnar(
                "s",
                np.full(len(half), proposal.proposal_id, np.int64),
                gids,
                np.array([v.vote for v in half]),
                NOW + 10,
                wire_votes=[v.encode() for v in half],
            )
            assert (statuses == int(StatusCode.OK)).all()
        exported = engine.get_proposal("s", proposal.proposal_id)
        assert [v.vote_owner for v in exported.votes] == [
            v.vote_owner for v in votes
        ]
        # Chain-validate locally as a peer would.
        from hashgraph_tpu.protocol import validate_vote_chain

        validate_vote_chain(exported.votes)

    def test_multi_scope_churn_regossip_chain_validates(self):
        """wire_votes on ingest_columnar_multi (config-5 churn shape): a
        256-scope mixed batch retains per-row bytes, and every scope's
        proposal re-gossips with a chain-valid vote list that a second
        engine fully validates (reference: src/utils.rs:175-215). Before
        r5 the multi-scope entry point had no wire_votes parameter, so
        streaming deployments had to fall back to per-scope calls."""
        from hashgraph_tpu import Proposal

        n_scopes = 256
        engine_a = make_engine(capacity=512, voter_capacity=8)
        engine_b = make_engine(capacity=512, voter_capacity=8)
        scopes = [f"s{i}" for i in range(n_scopes)]
        batches = engine_a.create_proposals_multi(
            [(s, [request(n=4)]) for s in scopes], NOW
        )
        signers = [random_stub_signer() for _ in range(3)]
        col_pids, col_sidx, col_gids, col_vals, wire = [], [], [], [], []
        votes_of = {}
        for k, (scope, (proposal,)) in enumerate(zip(scopes, batches)):
            votes = self._chained_votes(proposal, signers, NOW + 1)
            votes_of[scope] = votes
            for v in votes:
                col_pids.append(proposal.proposal_id)
                col_sidx.append(k)
                col_gids.append(engine_a.voter_gid(v.vote_owner))
                col_vals.append(v.vote)
                wire.append(v.encode())
        statuses = engine_a.ingest_columnar_multi(
            scopes,
            np.array(col_sidx, np.int64),
            np.array(col_pids, np.int64),
            np.array(col_gids, np.int64),
            np.array(col_vals, bool),
            NOW + 10,
            wire_votes=wire,
        )
        assert (statuses == int(StatusCode.OK)).all(), statuses
        for k, (scope, (proposal,)) in enumerate(zip(scopes, batches)):
            exported = engine_a.get_proposal(scope, proposal.proposal_id)
            assert len(exported.votes) == 3
            assert [v.vote_owner for v in exported.votes] == [
                v.vote_owner for v in votes_of[scope]
            ]
            engine_b.process_incoming_proposal(
                scope, Proposal.decode(exported.encode()), NOW + 11
            )
            assert (
                engine_b.get_consensus_result(scope, proposal.proposal_id)
                is True
            )

    def test_malformed_offsets_fail_before_any_state_mutates(self):
        """A (packed, offsets) pair with negative or non-monotone offsets
        must fail the whole call up front — not apply votes and then strand
        them without retained bytes (or retain garbage slices)."""
        import pytest

        engine = make_engine()
        proposal = engine.create_proposal("s", request(n=4), NOW)
        signers = [random_stub_signer() for _ in range(2)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        packed = b"".join(v.encode() for v in votes)
        bad_offsets = np.array(
            [len(votes[0].encode()), 0, len(packed)], np.int64
        )  # decreasing
        with pytest.raises(ValueError, match="non-decreasing"):
            engine.ingest_columnar(
                "s",
                np.full(len(votes), proposal.proposal_id, np.int64),
                np.array([engine.voter_gid(v.vote_owner) for v in votes]),
                np.array([v.vote for v in votes]),
                NOW + 10,
                wire_votes=(packed, bad_offsets),
            )
        # Nothing was applied: the same rows are still ingestable.
        statuses = engine.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            np.array([engine.voter_gid(v.vote_owner) for v in votes]),
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=[v.encode() for v in votes],
        )
        assert (statuses == int(StatusCode.OK)).all()

    def test_mixed_scalar_and_columnar_exports_true_arrival_order(self):
        """A session fed through BOTH paths — scalar vote, columnar chunk,
        scalar vote, columnar chunk — must export its votes in true arrival
        order (not path-concatenated), chain-valid at a peer."""
        from hashgraph_tpu import Proposal
        from hashgraph_tpu.protocol import validate_vote_chain

        engine = make_engine()
        peer = make_engine()
        # n=8, liveness NO: 5 YES of 8 never decides mid-stream (req 6).
        proposal = engine.create_proposal("s", request(n=8, liveness=False), NOW)
        signers = [random_stub_signer() for _ in range(5)]
        votes = self._chained_votes(proposal, signers, NOW + 1)

        def columnar(vs):
            gids = np.array([engine.voter_gid(v.vote_owner) for v in vs])
            st = engine.ingest_columnar(
                "s",
                np.full(len(vs), proposal.proposal_id, np.int64),
                gids,
                np.array([v.vote for v in vs]),
                NOW + 10,
                wire_votes=[v.encode() for v in vs],
            )
            assert (st == int(StatusCode.OK)).all(), st

        # arrival: scalar v0 | columnar [v1, v2] | scalar v3 | columnar [v4]
        engine.process_incoming_vote("s", votes[0], NOW + 9)
        columnar(votes[1:3])
        engine.process_incoming_vote("s", votes[3], NOW + 9)
        columnar(votes[4:5])

        exported = engine.get_proposal("s", proposal.proposal_id)
        assert [v.vote_owner for v in exported.votes] == [
            v.vote_owner for v in votes
        ]
        validate_vote_chain(exported.votes)
        peer.process_incoming_proposal(
            "s", Proposal.decode(exported.encode()), NOW + 11
        )
        assert (
            peer.get_scope_stats("s").total_sessions == 1
        )  # full gauntlet passed

    def test_no_retention_without_opt_in(self):
        engine = make_engine()
        proposal = engine.create_proposal("s", request(n=3), NOW)
        signers = [random_stub_signer() for _ in range(2)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        gids = np.array([engine.voter_gid(v.vote_owner) for v in votes])
        engine.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
        )
        assert engine.get_proposal("s", proposal.proposal_id).votes == []

    def test_checkpoint_roundtrip_preserves_retained_chain_and_pooled_tallies(self):
        """save/load must not drop the re-gossip capability: retained votes
        export as real signed votes, unretained pooled rows as tallies."""
        from hashgraph_tpu import InMemoryConsensusStorage, Proposal

        engine = make_engine()
        proposal = engine.create_proposal("s", request(n=4), NOW)
        signers = [random_stub_signer() for _ in range(3)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        gids = np.array([engine.voter_gid(v.vote_owner) for v in votes])
        statuses = engine.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=[v.encode() for v in votes],
        )
        assert (statuses == int(StatusCode.OK)).all()

        # Also a tally-only (unretained) session in the same scope.
        plain = engine.create_proposal("s", request(n=4, name="plain"), NOW)
        owner = b"\x55" * 20
        engine.ingest_columnar(
            "s",
            np.array([plain.proposal_id], np.int64),
            np.array([engine.voter_gid(owner)]),
            np.array([True]),
            NOW + 10,
        )

        storage = InMemoryConsensusStorage()
        engine.save_to_storage(storage)
        restored = make_engine()
        restored.load_from_storage(storage)

        # The retained chain survives: the restored engine re-gossips a
        # proposal that chain-validates at a fresh peer.
        exported = restored.get_proposal("s", proposal.proposal_id)
        assert [v.vote_owner for v in exported.votes] == [
            v.vote_owner for v in votes
        ]
        peer = make_engine()
        peer.process_incoming_proposal(
            "s", Proposal.decode(exported.encode()), NOW + 11
        )
        assert peer.get_consensus_result("s", proposal.proposal_id) is True
        # The unretained session round-trips its device tallies.
        session = restored.export_session("s", plain.proposal_id)
        assert session.tallies == {owner: True}

    def test_packed_wire_votes_form(self):
        engine = make_engine()
        proposal = engine.create_proposal("s", request(n=4), NOW)
        signers = [random_stub_signer() for _ in range(3)]
        votes = self._chained_votes(proposal, signers, NOW + 1)
        encoded = [v.encode() for v in votes]
        packed = b"".join(encoded)
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
        gids = np.array([engine.voter_gid(v.vote_owner) for v in votes])
        statuses = engine.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=(packed, offsets),
        )
        assert (statuses == int(StatusCode.OK)).all()
        exported = engine.get_proposal("s", proposal.proposal_id)
        assert [v.encode() for v in exported.votes] == encoded


class TestFreshDispatchRouting:
    def test_fresh_batch_takes_closed_form_dispatch(self):
        """Regression guard: the common columnar shape (fresh slots, unique
        voters) must route through the closed-form kernel — a silent fall
        back to the segmented scan would be a large perf regression that no
        correctness test would catch."""
        from hashgraph_tpu.tracing import Tracer

        engine = make_engine(capacity=32, voter_capacity=8)
        engine.tracer = Tracer(enabled=True)
        proposals = engine.create_proposals("s", [request(n=6)] * 4, NOW)
        gids = np.array(
            [engine.voter_gid(bytes([i]) * 4) for i in range(1, 5)], np.int64
        )
        pids = np.repeat(
            np.array([p.proposal_id for p in proposals], np.int64), 4
        )
        statuses = engine.ingest_columnar(
            "s", pids, np.tile(gids, 4), np.ones(16, bool), NOW + 1
        )
        assert (statuses == int(StatusCode.OK)).all()
        assert engine.tracer.counters().get("engine.fresh_dispatches") == 1

        # Second batch on the SAME (now non-fresh) slots: falls back to the
        # general path, statuses still exact (dups rejected).
        statuses = engine.ingest_columnar(
            "s", pids, np.tile(gids, 4), np.ones(16, bool), NOW + 1
        )
        assert engine.tracer.counters().get("engine.fresh_dispatches") == 1
        assert (
            (statuses == int(StatusCode.DUPLICATE_VOTE))
            | (statuses == int(StatusCode.ALREADY_REACHED))
        ).all()

    def test_decided_empty_session_rejects_via_fallback(self):
        """A session decided with ZERO votes (liveness timeout) still has
        fresh lane tables, so the fast lane path engages — but the state
        check must route the dispatch to the scan kernel, which reports the
        late votes as ALREADY_REACHED."""
        from hashgraph_tpu.tracing import Tracer

        engine = make_engine(capacity=8, voter_capacity=4)
        engine.tracer = Tracer(enabled=True)
        proposal = engine.create_proposal("s", request(n=3, exp=10), NOW)
        swept = engine.sweep_timeouts(NOW + 100)
        assert swept and swept[0][2] is True  # liveness YES fills silents
        gid = engine.voter_gid(b"\x09" * 4)
        statuses = engine.ingest_columnar(
            "s",
            np.array([proposal.proposal_id]),
            np.array([gid]),
            np.array([True]),
            NOW + 101,
        )
        assert statuses.tolist() == [int(StatusCode.ALREADY_REACHED)]
        assert not engine.tracer.counters().get("engine.fresh_dispatches")


class TestLaneBatchResolution:
    def test_mixed_existing_and_new(self):
        from hashgraph_tpu.engine import ProposalPool

        pool = ProposalPool(4, 3)
        pool.allocate_batch(
            keys=["a", "b"],
            n=np.array([3, 3]),
            req=np.array([2, 2]),
            cap=np.array([2, 2]),
            gossip=np.array([True, True]),
            liveness=np.array([True, True]),
            expiry=np.array([100, 100]),
            created_at=np.array([0, 0]),
        )
        g = [pool.voter_gid(bytes([i]) * 4) for i in range(6)]
        # Scalar assignment first.
        assert pool.lane_for(0, bytes([0]) * 4) == 0
        # Batch: slot0 sees existing gid0 + new gid1; slot1 all new; then
        # gid1 repeats on slot0 (same lane), overflow on slot1.
        lanes = pool.lanes_for_batch(
            np.array([0, 0, 1, 1, 0, 1, 1]),
            np.array([g[0], g[1], g[2], g[3], g[1], g[4], g[5]]),
        )
        assert list(lanes) == [0, 1, 0, 1, 1, 2, -1]
        # Scalar sees batch assignments.
        assert pool.lane_for(1, bytes([2]) * 4) == 0
        assert pool.lane_for(0, bytes([1]) * 4) == 1

    def test_huge_gid_does_not_corrupt_packed_keys(self):
        """Advisor r2 low: a gid >= 2^31 must not sign-extend into the slot
        bits of the (slot << 32) | gid dedup key."""
        from hashgraph_tpu.engine import ProposalPool

        pool = ProposalPool(4, 3)
        pool.allocate_batch(
            keys=["a", "b"],
            n=np.array([3, 3]),
            req=np.array([2, 2]),
            cap=np.array([2, 2]),
            gossip=np.array([True, True]),
            liveness=np.array([True, True]),
            expiry=np.array([100, 100]),
            created_at=np.array([0, 0]),
        )
        big = 2**31 + 5  # int32-wraps to negative
        lanes = pool.lanes_for_batch(
            np.array([0, 1, 0]), np.array([big, big, big])
        )
        # Same gid: fresh lane per slot, repeat resolves to the same lane.
        assert list(lanes) == [0, 0, 0]
        assert pool._lane_count[0] == 1 and pool._lane_count[1] == 1
