"""Simulated multi-peer convergence (reference: tests/network_gossip_tests.rs):
independent services per peer, messages hand-ferried as wire bytes."""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    Proposal,
    Vote,
)
from hashgraph_tpu.errors import InsufficientVotesAtTimeout

from common import NOW, make_service, sibling_service

SCOPE = "gossip_scope"


def create_on(service, n, liveness=True):
    request = CreateProposalRequest(
        name="Gossip",
        payload=b"",
        proposal_owner=service.signer().identity(),
        expected_voters_count=n,
        expiration_timestamp=120,
        liveness_criteria_yes=liveness,
    )
    return service.create_proposal_with_config(
        SCOPE, request, ConsensusConfig.gossipsub(), NOW
    )


def ferry_proposal(src_proposal: Proposal, dst_service):
    """Serialize and deliver a proposal as the network would."""
    dst_service.process_incoming_proposal(
        SCOPE, Proposal.decode(src_proposal.encode()), NOW
    )


def ferry_vote(vote: Vote, dst_service):
    dst_service.process_incoming_vote(SCOPE, Vote.decode(vote.encode()), NOW)


def test_two_peer_unanimous_yes():
    """reference: tests/network_gossip_tests.rs:21-76"""
    alice = make_service()
    bob = make_service()  # separate storage: a genuinely remote peer

    proposal = create_on(alice, 2)
    vote_a = alice.cast_vote(SCOPE, proposal.proposal_id, True, NOW)

    # Bob receives the updated proposal (with Alice's vote embedded).
    ferry_proposal(alice.storage().get_proposal(SCOPE, proposal.proposal_id), bob)
    vote_b = bob.cast_vote(SCOPE, proposal.proposal_id, True, NOW)

    # Alice receives Bob's vote.
    ferry_vote(vote_b, alice)

    assert alice.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True
    assert bob.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True
    assert vote_a.vote_owner != vote_b.vote_owner


def test_three_peer_out_of_order_delivery():
    """reference: tests/network_gossip_tests.rs:81-152 — votes arrive in
    different orders at different peers, all converge."""
    alice, bob, carol = make_service(), make_service(), make_service()

    proposal = create_on(alice, 3)
    raw = alice.storage().get_proposal(SCOPE, proposal.proposal_id)
    ferry_proposal(raw, bob)
    ferry_proposal(raw, carol)

    vote_a = alice.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
    vote_b = bob.cast_vote(SCOPE, proposal.proposal_id, True, NOW)

    # Carol gets B then A; Alice gets B; Bob gets A.
    ferry_vote(vote_b, carol)
    ferry_vote(vote_a, carol)
    ferry_vote(vote_b, alice)
    ferry_vote(vote_a, bob)

    for peer in (alice, bob, carol):
        assert peer.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True


def test_multi_peer_timeout_converges_to_failed():
    """reference: tests/network_gossip_tests.rs:159-254 — insufficient votes +
    liveness=False tie -> every peer's timeout lands on Failed."""
    peers = [make_service() for _ in range(3)]
    proposal = create_on(peers[0], 4, liveness=False)
    raw = peers[0].storage().get_proposal(SCOPE, proposal.proposal_id)
    for p in peers[1:]:
        ferry_proposal(raw, p)

    # Two YES votes gossiped everywhere; 2 silent-as-NO -> weighted tie.
    v0 = peers[0].cast_vote(SCOPE, proposal.proposal_id, True, NOW)
    v1 = peers[1].cast_vote(SCOPE, proposal.proposal_id, True, NOW)
    from hashgraph_tpu.errors import DuplicateVote

    for vote in (v0, v1):
        for p in peers:
            try:
                ferry_vote(vote, p)
            except DuplicateVote:
                pass  # the casting peer already holds its own vote

    for p in peers:
        with pytest.raises(InsufficientVotesAtTimeout):
            p.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60)


def test_tie_resolved_yes_by_liveness_everywhere():
    """reference: tests/network_gossip_tests.rs:259-377"""
    shared = make_service()
    peers = [shared] + [sibling_service(shared) for _ in range(3)]

    proposal = create_on(peers[0], 4, liveness=True)
    for i, choice in enumerate([True, True, False, False]):
        peers[i].cast_vote(SCOPE, proposal.proposal_id, choice, NOW)

    # 2-2 with everyone voted: tie broken YES by liveness.
    assert shared.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True
