"""Golden tests for the scalar protocol kernels.

Cases transcribed from the reference's pure-function suites
(reference: tests/threshold_tests.rs, tests/rfc_compliance_tests.rs:361-372,
src/utils.rs:369-396). These tables are the bit-exactness oracle for the
vectorized TPU kernels.
"""

import pytest

from hashgraph_tpu.errors import (
    InvalidConsensusThreshold,
    InvalidExpectedVotersCount,
    InvalidTimeout,
    ParentHashMismatch,
    ProposalExpired,
    ReceivedHashMismatch,
)
from hashgraph_tpu.protocol import (
    calculate_consensus_result,
    calculate_max_rounds,
    calculate_threshold_based_value,
    compute_vote_hash,
    decide,
    fold_u128_to_u32,
    generate_id,
    has_sufficient_votes,
    validate_expected_voters_count,
    validate_proposal_timestamp,
    validate_threshold,
    validate_timeout,
    validate_vote_chain,
)
from hashgraph_tpu.wire import Vote

TWO_THIRDS = 2.0 / 3.0


def yes_vote(i: int) -> Vote:
    return Vote(
        vote_id=i,
        vote_owner=bytes([i]),
        proposal_id=1,
        timestamp=0,
        vote=True,
        vote_hash=bytes([i]),
    )


def no_vote(i: int) -> Vote:
    v = yes_vote(i)
    v.vote = False
    return v


def result_of(votes, n, threshold=TWO_THIRDS, liveness=True, is_timeout=False):
    return calculate_consensus_result(
        {v.vote_owner: v for v in votes}, n, threshold, liveness, is_timeout
    )


class TestThresholdRounding:
    """reference: tests/threshold_tests.rs:9-38"""

    def test_two_thirds_threshold_rounding(self):
        t = TWO_THIRDS
        assert has_sufficient_votes(1, 1, t)
        assert not has_sufficient_votes(1, 2, t)
        assert has_sufficient_votes(2, 2, t)
        assert not has_sufficient_votes(1, 3, t)
        assert has_sufficient_votes(2, 3, t)
        assert not has_sufficient_votes(2, 4, t)
        assert has_sufficient_votes(3, 4, t)
        assert not has_sufficient_votes(3, 5, t)
        assert has_sufficient_votes(4, 5, t)
        assert not has_sufficient_votes(3, 6, t)
        assert has_sufficient_votes(4, 6, t)
        assert not has_sufficient_votes(66, 100, t)
        assert has_sufficient_votes(67, 100, t)

    def test_ceil_2n3_table(self):
        """reference: tests/rfc_compliance_tests.rs:361-372"""
        expected = {1: 1, 2: 2, 3: 2, 4: 3, 5: 4, 6: 4, 7: 5, 8: 6, 9: 6, 10: 7}
        for n, want in expected.items():
            assert calculate_threshold_based_value(n, TWO_THIRDS) == want
            assert calculate_max_rounds(n, TWO_THIRDS) == want

    def test_exact_integer_path_vs_float_path(self):
        # The 2/3 special case must use integer div_ceil — for huge n the f64
        # path would round differently.
        for n in [3, 6, 9, 999, 3 * 10**8]:
            assert calculate_threshold_based_value(n, TWO_THIRDS) == (2 * n + 2) // 3
        # Non-2/3 thresholds take the f64 ceil path.
        assert calculate_threshold_based_value(5, 0.9) == 5
        assert calculate_threshold_based_value(5, 0.5) == 3
        assert calculate_threshold_based_value(10, 0.61) == 7


class TestConsensusResultVariants:
    """reference: tests/threshold_tests.rs:41-165"""

    def test_majority_yes(self):
        assert result_of([yes_vote(1), yes_vote(2), no_vote(3)], 3, liveness=False) is True

    def test_majority_no(self):
        assert result_of([yes_vote(1), no_vote(2), no_vote(3)], 3, liveness=True) is False

    def test_n2_tie_is_not_unanimous_yes(self):
        votes = [yes_vote(1), no_vote(2)]
        assert result_of(votes, 2, liveness=True) is False
        assert result_of(votes, 2, liveness=False) is False

    def test_strict_threshold_requires_more_yes(self):
        votes = [yes_vote(1), yes_vote(2), yes_vote(3), no_vote(4), no_vote(5)]
        assert result_of(votes, 5, threshold=0.9) is None

    def test_fast_threshold_resolves_early(self):
        votes = [yes_vote(1), yes_vote(2), no_vote(3)]
        assert result_of(votes, 5, threshold=0.5) is True

    def test_n2_timeout_still_requires_all_votes(self):
        assert result_of([yes_vote(1)], 2, is_timeout=True) is None

    def test_quorum_not_met_without_timeout(self):
        votes = [yes_vote(1), yes_vote(2)]
        assert result_of(votes, 4, liveness=True, is_timeout=False) is None

    def test_timeout_silent_as_yes(self):
        votes = [yes_vote(1), yes_vote(2)]
        assert result_of(votes, 4, liveness=True, is_timeout=True) is True

    def test_timeout_silent_as_no_splits_evenly(self):
        votes = [yes_vote(1), yes_vote(2)]
        assert result_of(votes, 4, liveness=False, is_timeout=True) is None

    def test_timeout_one_yes_one_no_two_silent_yes(self):
        votes = [yes_vote(1), no_vote(2)]
        assert result_of(votes, 4, liveness=True, is_timeout=True) is True

    def test_timeout_weighted_tie_is_none(self):
        votes = [yes_vote(1), no_vote(2), no_vote(3)]
        assert result_of(votes, 4, liveness=True, is_timeout=True) is None

    def test_n1_unanimity(self):
        assert result_of([yes_vote(1)], 1) is True
        assert result_of([no_vote(1)], 1) is False
        assert result_of([], 1) is None

    def test_full_tie_breaks_by_liveness(self):
        # n=4, 2 yes 2 no, everyone voted -> tie broken by liveness flag.
        votes = [yes_vote(1), yes_vote(2), no_vote(3), no_vote(4)]
        assert result_of(votes, 4, liveness=True) is True
        assert result_of(votes, 4, liveness=False) is False

    def test_decide_count_form_matches_vote_form(self):
        for n in range(1, 8):
            for total in range(0, n + 1):
                for yes in range(0, total + 1):
                    for liveness in (True, False):
                        for is_timeout in (True, False):
                            votes = [yes_vote(i) for i in range(yes)] + [
                                no_vote(100 + i) for i in range(total - yes)
                            ]
                            assert decide(
                                yes, total, n, TWO_THIRDS, liveness, is_timeout
                            ) == result_of(
                                votes, n, liveness=liveness, is_timeout=is_timeout
                            )


class TestIdGeneration:
    def test_fold_does_not_collapse_distinct_values(self):
        """reference: src/utils.rs:375-396"""
        low = 0xDEADBEEF
        a = (0x00000001 << 32) | low
        b = (0xABCDEF01 << 32) | low
        assert fold_u128_to_u32(a) != fold_u128_to_u32(b)

    def test_generate_id_is_u32(self):
        for _ in range(100):
            assert 0 <= generate_id() <= 0xFFFFFFFF


class TestVoteHash:
    def test_deterministic_and_field_sensitive(self):
        v = Vote(
            vote_id=7,
            vote_owner=b"\x01\x02",
            proposal_id=9,
            timestamp=1234,
            vote=True,
            parent_hash=b"p",
            received_hash=b"r",
        )
        h1 = compute_vote_hash(v)
        assert len(h1) == 32
        assert compute_vote_hash(v) == h1
        v2 = v.clone()
        v2.vote = False
        assert compute_vote_hash(v2) != h1
        v3 = v.clone()
        v3.signature = b"sig-does-not-matter"
        assert compute_vote_hash(v3) == h1

    def test_known_digest(self):
        # Pinned digest: sha256(vote_id_le || owner || proposal_id_le ||
        # timestamp_le || [vote] || parent || received)
        import hashlib

        v = Vote(vote_id=1, vote_owner=b"o", proposal_id=2, timestamp=3, vote=True)
        manual = hashlib.sha256(
            (1).to_bytes(4, "little")
            + b"o"
            + (2).to_bytes(4, "little")
            + (3).to_bytes(8, "little")
            + b"\x01"
        ).digest()
        assert compute_vote_hash(v) == manual


class TestVoteChain:
    def _mk(self, owner: bytes, ts: int, vote_hash: bytes, parent=b"", received=b""):
        return Vote(
            vote_owner=owner,
            timestamp=ts,
            vote_hash=vote_hash,
            parent_hash=parent,
            received_hash=received,
        )

    def test_short_chains_pass(self):
        validate_vote_chain([])
        validate_vote_chain([self._mk(b"a", 1, b"h1")])

    def test_valid_received_chain(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"b", 2, b"h2", received=b"h1")
        v3 = self._mk(b"c", 3, b"h3", received=b"h2")
        validate_vote_chain([v1, v2, v3])

    def test_received_hash_mismatch(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"b", 2, b"h2", received=b"WRONG")
        with pytest.raises(ReceivedHashMismatch):
            validate_vote_chain([v1, v2])

    def test_received_timestamp_regression(self):
        v1 = self._mk(b"a", 10, b"h1")
        v2 = self._mk(b"b", 5, b"h2", received=b"h1")
        with pytest.raises(ReceivedHashMismatch):
            validate_vote_chain([v1, v2])

    def test_empty_received_hash_skips_adjacency(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"b", 2, b"h2", received=b"")
        validate_vote_chain([v1, v2])

    def test_valid_parent_chain_same_owner(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"b", 2, b"h2", received=b"h1")
        v3 = self._mk(b"a", 3, b"h3", parent=b"h1", received=b"h2")
        validate_vote_chain([v1, v2, v3])

    def test_parent_owner_mismatch(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"b", 2, b"h2", parent=b"h1", received=b"h1")
        with pytest.raises(ParentHashMismatch):
            validate_vote_chain([v1, v2])

    def test_parent_unknown_hash(self):
        v1 = self._mk(b"a", 1, b"h1")
        v2 = self._mk(b"a", 2, b"h2", parent=b"NOPE", received=b"h1")
        with pytest.raises(ParentHashMismatch):
            validate_vote_chain([v1, v2])

    def test_parent_must_be_earlier_index(self):
        # Parent resolving to a later-indexed vote is rejected.
        v1 = self._mk(b"a", 1, b"h1", parent=b"h2")
        v2 = self._mk(b"a", 1, b"h2", received=b"h1")
        with pytest.raises(ParentHashMismatch):
            validate_vote_chain([v1, v2])

    def test_parent_timestamp_regression(self):
        v1 = self._mk(b"a", 10, b"h1")
        v2 = self._mk(b"b", 10, b"h2", received=b"h1")
        v3 = self._mk(b"a", 5, b"h3", parent=b"h1", received=b"")
        with pytest.raises(ParentHashMismatch):
            validate_vote_chain([v1, v2, v3])


class TestValidators:
    def test_proposal_timestamp(self):
        validate_proposal_timestamp(100, 99)
        with pytest.raises(ProposalExpired):
            validate_proposal_timestamp(100, 100)
        with pytest.raises(ProposalExpired):
            validate_proposal_timestamp(100, 101)

    def test_threshold_bounds(self):
        validate_threshold(0.0)
        validate_threshold(1.0)
        validate_threshold(TWO_THIRDS)
        with pytest.raises(InvalidConsensusThreshold):
            validate_threshold(-0.01)
        with pytest.raises(InvalidConsensusThreshold):
            validate_threshold(1.01)

    def test_timeout_positive(self):
        validate_timeout(1)
        validate_timeout(0.5)
        with pytest.raises(InvalidTimeout):
            validate_timeout(0)

    def test_expected_voters_positive(self):
        validate_expected_voters_count(1)
        with pytest.raises(InvalidExpectedVotersCount):
            validate_expected_voters_count(0)
