"""Placement determinism for the scope-sharded fleet (parallel.fleet).

Two load-bearing properties:

- **Restart stability**: scope→shard assignment is a pure function of the
  (scope bytes, shard-id set) — no dependence on Python's randomized
  ``hash()``, process state, or insertion order. Pinned golden values
  catch an accidental algorithm change; a subprocess check proves a fresh
  interpreter (different PYTHONHASHSEED) computes identical placements.
- **Rendezvous invariant**: removing a shard remaps ONLY the scopes it
  owned; adding a shard moves scopes ONLY onto the new shard. This is
  what makes peer-set membership elastic — a resize never reshuffles
  unrelated scopes' traffic.

Pure host-side hashing: no jax, no devices.
"""

import subprocess
import sys

import pytest

from hashgraph_tpu.parallel.fleet import ScopePlacement, rendezvous_owner

SCOPES = [f"scope-{i}" for i in range(200)]


# ── Restart stability ──────────────────────────────────────────────────

# Golden assignments pinned at introduction: a change here is a placement
# algorithm change, which REMAPS EVERY DEPLOYED FLEET'S TRAFFIC — bump
# only with a migration story.
GOLDEN_4 = {
    "alpha": "shard-0",
    "beta": "shard-1",
    "gamma": "shard-0",
    "delta": "shard-2",
    "orders": "shard-1",
    "payments": "shard-3",
}


def test_golden_assignments_pinned():
    ids = ["shard-0", "shard-1", "shard-2", "shard-3"]
    assert {s: rendezvous_owner(s, ids) for s in GOLDEN_4} == GOLDEN_4


def test_assignment_ignores_shard_list_order():
    ids = ["shard-0", "shard-1", "shard-2", "shard-3"]
    for scope in SCOPES[:50]:
        assert rendezvous_owner(scope, ids) == rendezvous_owner(
            scope, list(reversed(ids))
        )


def test_shard_ids_longer_than_blake2b_key_are_rejected():
    """blake2b keys cap at 64 bytes: two ids sharing a 64-byte prefix
    would silently tie on EVERY scope (one shard starves). Must be a
    construction-time error, not a silent truncation."""
    long_a = "rack-" + "x" * 70 + "-a"
    assert len(long_a.encode()) > 64
    with pytest.raises(ValueError, match="64 bytes"):
        rendezvous_owner("s", ["ok", long_a])
    with pytest.raises(ValueError, match="64 bytes"):
        ScopePlacement([long_a])
    placement = ScopePlacement(["a", "b"])
    with pytest.raises(ValueError, match="64 bytes"):
        placement.add_shard(long_a)
    # 64 bytes exactly is fine.
    edge = "y" * 64
    assert rendezvous_owner("s", ["a", edge]) in ("a", edge)


def test_scope_types_are_canonicalized():
    ids = ["a", "b", "c"]
    # str/bytes/int canonical forms are distinct namespaces (multihost
    # _canonical_scope_bytes discipline), each deterministic.
    assert rendezvous_owner("7", ids) == rendezvous_owner("7", ids)
    assert rendezvous_owner(7, ids) == rendezvous_owner(7, ids)
    with pytest.raises(TypeError):
        rendezvous_owner(object(), ids)
    with pytest.raises(ValueError):
        rendezvous_owner("s", [])


def test_placement_stable_across_process_restart():
    """A fresh interpreter (fresh PYTHONHASHSEED) must compute the exact
    same 200-scope placement — the property that lets two peers (or one
    peer before and after a restart) route without coordination."""
    ids = ["shard-0", "shard-1", "shard-2", "shard-3", "shard-4"]
    local = ",".join(rendezvous_owner(s, ids) for s in SCOPES)
    script = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "from hashgraph_tpu.parallel.fleet import rendezvous_owner\n"
        f"ids = {ids!r}\n"
        f"scopes = [f'scope-{{i}}' for i in range(200)]\n"
        "print(','.join(rendezvous_owner(s, ids) for s in scopes))\n"
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", script, repo],
        capture_output=True,
        text=True,
        check=True,
        env={**os.environ, "PYTHONHASHSEED": "12345"},
    )
    assert out.stdout.strip() == local


# ── Rendezvous invariant ───────────────────────────────────────────────


@pytest.mark.parametrize("n_shards", [2, 3, 5, 9])
def test_remove_shard_remaps_only_its_scopes(n_shards):
    ids = [f"shard-{k}" for k in range(n_shards)]
    before = {s: rendezvous_owner(s, ids) for s in SCOPES}
    for removed in ids:
        survivors = [sid for sid in ids if sid != removed]
        for scope in SCOPES:
            after = rendezvous_owner(scope, survivors)
            if before[scope] != removed:
                # Not owned by the removed shard: owner unchanged.
                assert after == before[scope], (scope, removed)
            else:
                assert after != removed


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_add_shard_moves_scopes_only_onto_new_shard(n_shards):
    ids = [f"shard-{k}" for k in range(n_shards)]
    before = {s: rendezvous_owner(s, ids) for s in SCOPES}
    grown = ids + ["shard-new"]
    moved = 0
    for scope in SCOPES:
        after = rendezvous_owner(scope, grown)
        if after != before[scope]:
            assert after == "shard-new", scope
            moved += 1
    if n_shards <= 4:
        # Expected steal fraction is 1/(n+1); with 200 scopes the count
        # being zero would itself be a red flag for the hash spreading.
        assert moved > 0


def test_distribution_is_roughly_balanced():
    ids = [f"shard-{k}" for k in range(4)]
    counts = {sid: 0 for sid in ids}
    for scope in SCOPES:
        counts[rendezvous_owner(scope, ids)] += 1
    # 200 scopes over 4 shards: E=50 per shard; a keyed-64-bit-digest HRW
    # should not be wildly skewed (loose 3x bound, not a chi-square test).
    assert all(15 <= c <= 110 for c in counts.values()), counts


# ── ScopePlacement wrapper ─────────────────────────────────────────────


def test_scope_placement_membership_and_cache():
    placement = ScopePlacement(["a", "b"])
    owners = {s: placement.owner(s) for s in SCOPES[:40]}
    # Memoized: repeat lookups agree.
    assert {s: placement.owner(s) for s in SCOPES[:40]} == owners
    placement.add_shard("c")
    for scope, prior in owners.items():
        after = placement.owner(scope)
        assert after in ("c", prior)  # rendezvous invariant through the API
    with pytest.raises(ValueError):
        placement.add_shard("c")
    placement.remove_shard("c")
    assert {s: placement.owner(s) for s in SCOPES[:40]} == owners
    with pytest.raises(ValueError):
        placement.remove_shard("zz")
    placement.remove_shard("b")
    with pytest.raises(ValueError):
        placement.remove_shard("a")  # never below one shard
    with pytest.raises(ValueError):
        ScopePlacement([])
