"""Consensus health observatory (hashgraph_tpu.obs.health): peer
scorecards, equivocation/fork evidence, liveness watchdog, alert rules,
and their surfaces (engine.health_report, OP_HEALTH, enriched /healthz).

Every test builds its engines with a PRIVATE HealthMonitor (the process
default is shared across the whole test session by design, like the
metrics registry); bridge tests pass one per server the same way.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.bridge import BridgeClient, BridgeServer
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.obs import MetricsRegistry
from hashgraph_tpu.obs.health import (
    ALERTS_TOTAL,
    GRADE_FAULTY,
    GRADE_HEALTHY,
    GRADE_SUSPECT,
    KIND_EQUIVOCATION,
    KIND_FORK,
    AlertRule,
    HealthMonitor,
)
from hashgraph_tpu.protocol import compute_vote_hash
from hashgraph_tpu.wire import Vote

from common import NOW, random_stub_signer

OK = int(StatusCode.OK)


def fresh_monitor(**kwargs) -> HealthMonitor:
    kwargs.setdefault("registry", MetricsRegistry())
    return HealthMonitor(**kwargs)


def make_engine(monitor=None, cache="default", voters=16, **kwargs):
    return TpuConsensusEngine(
        StubConsensusSigner(b"\x42" * 20),
        capacity=32,
        voter_capacity=voters,
        verify_cache=cache,
        health_monitor=monitor if monitor is not None else fresh_monitor(),
        **kwargs,
    )


def make_request(expected=12, expiry=10_000):
    return CreateProposalRequest(
        name="p",
        payload=b"x",
        proposal_owner=b"o",
        expected_voters_count=expected,
        expiration_timestamp=expiry,
        liveness_criteria_yes=True,
    )


def make_chain(engine, n_votes=6, scope="s"):
    """(base proposal, fully grown chain) with n_votes chained votes from
    distinct stub signers."""
    proposal = engine.create_proposal(scope, make_request(), NOW)
    chain = proposal.clone()
    for i in range(n_votes):
        signer = StubConsensusSigner(bytes([i + 1]) * 20)
        chain.votes.append(build_vote(chain, bool(i % 2), signer, NOW + 1 + i))
    return proposal, chain


def grown(chain, k):
    p = chain.clone()
    p.votes = [v.clone() for v in chain.votes[:k]]
    return p


class TestScorecards:
    def test_admissions_and_last_seen(self):
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(4), NOW).proposal_id
        voter = StubConsensusSigner(b"\x07" * 20)
        vote = build_vote(engine.get_proposal("s", pid), True, voter, NOW + 5)
        assert int(engine.ingest_votes([("s", vote)], NOW + 5)[0]) == OK
        card = monitor.scorecard(voter.identity())
        assert card["votes_admitted"] == 1
        assert card["last_seen"] == NOW + 5
        assert card["grade"] == GRADE_HEALTHY

    def test_embedded_chain_counts_admissions(self):
        sender = make_engine()
        _, chain = make_chain(sender, n_votes=4)
        monitor = fresh_monitor()
        receiver = make_engine(monitor)
        receiver.process_incoming_proposal("r", grown(chain, 4), NOW + 20)
        for vote in chain.votes:
            card = monitor.scorecard(vote.vote_owner)
            assert card is not None and card["votes_admitted"] == 1

    def test_invalid_signature_marks_suspect(self):
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(4), NOW).proposal_id
        voter = StubConsensusSigner(b"\x07" * 20)
        vote = build_vote(engine.get_proposal("s", pid), True, voter, NOW + 1)
        vote.signature = b"\x00" * 65
        code = int(engine.ingest_votes([("s", vote)], NOW + 1)[0])
        assert code == int(StatusCode.INVALID_VOTE_SIGNATURE)
        card = monitor.scorecard(voter.identity())
        assert card["invalid_signatures"] == 1
        assert card["grade"] == GRADE_SUSPECT

    def test_expired_vote_scores_expired_gossip(self):
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(4, expiry=100), NOW).proposal_id
        voter = StubConsensusSigner(b"\x07" * 20)
        vote = build_vote(engine.get_proposal("s", pid), True, voter, NOW + 1)
        late = engine.get_proposal("s", pid).expiration_timestamp + 5
        code = int(engine.ingest_votes([("s", vote)], late)[0])
        assert code == int(StatusCode.VOTE_EXPIRED)
        assert monitor.scorecard(voter.identity())["expired_gossip"] == 1

    def test_bounded_peer_set_evicts_least_recently_seen(self):
        monitor = fresh_monitor(max_peers=4)
        for i in range(8):
            monitor.note_admitted({bytes([i]) * 20: 1}, NOW + i)
        assert monitor.peer_count() == 4
        assert monitor.scorecard(bytes([0]) * 20) is None
        assert monitor.scorecard(bytes([7]) * 20) is not None


class TestEquivocation:
    def _equivocate(self, monitor):
        """Drive two validly-signed conflicting votes from one signer
        through the vote path; returns (engine, pid, signer)."""
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(6), NOW).proposal_id
        signer = StubConsensusSigner(b"\x07" * 20)
        v1 = build_vote(engine.get_proposal("s", pid), True, signer, NOW + 1)
        assert int(engine.ingest_votes([("s", v1)], NOW + 1)[0]) == OK
        # Conflicting second vote: same signer, opposite value, new chain
        # position — validly signed, rejected as a duplicate by the
        # session, retained as evidence by the health layer.
        v2 = build_vote(engine.get_proposal("s", pid), False, signer, NOW + 2)
        code = int(engine.ingest_votes([("s", v2)], NOW + 2)[0])
        assert code in (
            int(StatusCode.DUPLICATE_VOTE),
            int(StatusCode.USER_ALREADY_VOTED),
        )
        return engine, pid, signer

    def test_equivocation_recorded_with_verified_evidence(self):
        monitor = fresh_monitor()
        engine, pid, signer = self._equivocate(monitor)
        card = monitor.scorecard(signer.identity())
        assert card["equivocations"] == 1
        assert card["grade"] == GRADE_FAULTY
        [record] = monitor.evidence()
        assert record["kind"] == KIND_EQUIVOCATION
        assert record["offender"] == signer.identity().hex()
        assert record["proposal_id"] == pid
        assert record["verified"] is True

    def test_evidence_is_self_authenticating(self):
        """The retained byte pair decodes to two signature-valid votes
        from the offender for the same proposal with different hashes —
        verifiable by any third party holding the scheme."""
        monitor = fresh_monitor()
        _, pid, signer = self._equivocate(monitor)
        [record] = monitor.evidence()
        a = Vote.decode(bytes.fromhex(record["vote_a"]))
        b = Vote.decode(bytes.fromhex(record["vote_b"]))
        assert a.vote_owner == b.vote_owner == signer.identity()
        assert a.proposal_id == b.proposal_id == pid
        assert a.vote_hash != b.vote_hash
        for vote in (a, b):
            assert vote.vote_hash == compute_vote_hash(vote)
            assert StubConsensusSigner.verify(
                vote.vote_owner, vote.signing_payload(), vote.signature
            )

    def test_redelivered_equivocation_dedups(self):
        monitor = fresh_monitor()
        engine, pid, signer = self._equivocate(monitor)
        # Gossip redelivers the same conflict: one evidence record, one
        # scorecard count.
        v2 = Vote.decode(bytes.fromhex(monitor.evidence()[0]["vote_b"]))
        engine.ingest_votes([("s", v2)], NOW + 3)
        assert monitor.evidence_count() == 1
        assert monitor.scorecard(signer.identity())["equivocations"] == 1

    def test_pre_validated_batches_cannot_mint_evidence(self):
        """pre_validated=True skips signature admission, so a forged
        conflicting vote fed through an embedder replay path must NOT
        become a verified evidence record / faulty grade (review
        finding: evidence must only come from votes THIS call
        signature-checked)."""
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(6), NOW).proposal_id
        signer = StubConsensusSigner(b"\x07" * 20)
        v1 = build_vote(engine.get_proposal("s", pid), True, signer, NOW + 1)
        assert int(engine.ingest_votes([("s", v1)], NOW + 1)[0]) == OK
        forged = build_vote(
            engine.get_proposal("s", pid), False, signer, NOW + 2
        )
        forged.signature = b"\x00" * 65  # never actually signed
        engine.ingest_votes([("s", forged)], NOW + 2, pre_validated=True)
        assert monitor.evidence_count() == 0
        assert monitor.scorecard(signer.identity())["grade"] == GRADE_HEALTHY

    def test_identical_redelivered_vote_is_not_equivocation(self):
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(6), NOW).proposal_id
        signer = StubConsensusSigner(b"\x07" * 20)
        vote = build_vote(engine.get_proposal("s", pid), True, signer, NOW + 1)
        assert int(engine.ingest_votes([("s", vote)], NOW + 1)[0]) == OK
        code = int(engine.ingest_votes([("s", vote.clone())], NOW + 2)[0])
        assert code == int(StatusCode.DUPLICATE_VOTE)
        assert monitor.evidence_count() == 0
        assert monitor.scorecard(signer.identity())["grade"] == GRADE_HEALTHY


class TestForkAndTruncation:
    def test_fork_redelivery_retains_evidence(self):
        """Fork conviction requires the double-sign bar: the divergent
        vote's owner must also have a DIFFERENT accepted vote in the
        session — then the retained pair is two votes signed by one
        identity, offline-verifiable misbehavior proof."""
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        proposal, chain = make_chain(engine, n_votes=6, scope="r")
        receiver_monitor = fresh_monitor()
        receiver = make_engine(receiver_monitor)
        assert receiver.deliver_proposal("r", grown(chain, 4), NOW + 20) == OK
        fork = grown(chain, 5)
        # The signer of accepted vote 2 double-signs: its position in the
        # fork carries a different vote by the SAME identity.
        forger = StubConsensusSigner(bytes([3]) * 20)
        assert chain.votes[2].vote_owner == forger.identity()
        fork.votes[2] = build_vote(proposal, True, forger, NOW + 40)
        code = receiver.deliver_proposal("r", fork, NOW + 41)
        assert code == int(StatusCode.PROPOSAL_ALREADY_EXIST)  # API unchanged
        [record] = receiver_monitor.evidence()
        assert record["kind"] == KIND_FORK
        assert record["offender"] == forger.identity().hex()
        assert record["verified"] is False  # captured crypto-free
        # The pair is the offender's ACCEPTED vote vs its divergent one —
        # both signed by the offender, self-authenticating offline.
        a = Vote.decode(bytes.fromhex(record["vote_a"]))
        b = Vote.decode(bytes.fromhex(record["vote_b"]))
        assert a.vote_hash == chain.votes[2].vote_hash
        assert a.vote_owner == forger.identity()
        assert b.vote_owner == forger.identity()
        assert a.vote_hash != b.vote_hash
        card = receiver_monitor.scorecard(forger.identity())
        assert card["fork_redeliveries"] == 1
        assert card["grade"] == GRADE_SUSPECT

    def test_divergence_by_unrelated_signer_is_not_evidence(self):
        """An honest vote can land at a different chain position under
        loss/reorder (or a racing embedder): a positional divergence
        whose signer has no other accepted vote proves nothing and must
        NOT defame that signer — no evidence, no scorecard hit, grade
        stays healthy (the chaos harness's zero-false-conviction bar)."""
        engine = make_engine()
        proposal, chain = make_chain(engine, n_votes=6, scope="r")
        receiver_monitor = fresh_monitor()
        receiver = make_engine(receiver_monitor)
        assert receiver.deliver_proposal("r", grown(chain, 4), NOW + 20) == OK
        fork = grown(chain, 5)
        stranger = StubConsensusSigner(b"\x91" * 20)
        fork.votes[2] = build_vote(proposal, True, stranger, NOW + 40)
        code = receiver.deliver_proposal("r", fork, NOW + 41)
        assert code == int(StatusCode.PROPOSAL_ALREADY_EXIST)
        assert receiver_monitor.evidence_count() == 0
        card = receiver_monitor.scorecard(stranger.identity())
        assert card is None or card["grade"] == GRADE_HEALTHY
        # The honest signer whose vote the fork displaced is untouched.
        displaced = receiver_monitor.scorecard(chain.votes[2].vote_owner)
        assert displaced is None or displaced["fork_redeliveries"] == 0

    def test_truncation_scores_chain_lag(self):
        engine = make_engine()
        _, chain = make_chain(engine, n_votes=6, scope="r")
        monitor = fresh_monitor()
        receiver = make_engine(monitor)
        assert receiver.deliver_proposal("r", grown(chain, 5), NOW + 20) == OK
        code = receiver.deliver_proposal("r", grown(chain, 2), NOW + 21)
        assert code == int(StatusCode.PROPOSAL_ALREADY_EXIST)
        # Attributed to the truncated chain's most recent signer.
        card = monitor.scorecard(chain.votes[1].vote_owner)
        assert card["truncation_redeliveries"] == 1
        assert card["chain_lag"] == 3 and card["max_chain_lag"] == 3
        assert monitor.evidence_count() == 0  # no signed conflict to keep

    def test_identical_redelivery_settles_without_prefix_walk(self):
        """The benign steady state must stay O(1): an identical
        redelivery is recognized by one tail-hash compare, never a
        per-vote prefix walk (the review's cost guard on PR 4's
        crypto-free settle)."""
        engine = make_engine()
        _, chain = make_chain(engine, n_votes=6, scope="r")
        monitor = fresh_monitor()
        receiver = make_engine(monitor)
        assert receiver.deliver_proposal("r", grown(chain, 6), NOW + 20) == OK
        redelivery = grown(chain, 6)
        walked = 0
        real_eq = type(chain.votes[0].vote_hash).__eq__

        class TattleBytes(bytes):
            def __eq__(self, other):
                nonlocal walked
                walked += 1
                return real_eq(bytes(self), other)

            __hash__ = bytes.__hash__

        for vote in redelivery.votes:
            vote.vote_hash = TattleBytes(vote.vote_hash)
        assert receiver.deliver_proposal("r", redelivery, NOW + 21) == int(
            StatusCode.PROPOSAL_ALREADY_EXIST
        )
        # Equal-length redeliveries bail on the length check alone in
        # _extension_suffix; the health probe adds ONE tail compare —
        # a full prefix walk would show >= 6 here.
        assert walked <= 2, walked

    def test_identical_redelivery_scores_nothing(self):
        engine = make_engine()
        _, chain = make_chain(engine, n_votes=4, scope="r")
        monitor = fresh_monitor()
        receiver = make_engine(monitor)
        assert receiver.deliver_proposal("r", grown(chain, 4), NOW + 20) == OK
        before = monitor.snapshot()
        code = receiver.deliver_proposal("r", grown(chain, 4), NOW + 21)
        assert code == int(StatusCode.PROPOSAL_ALREADY_EXIST)
        after = monitor.snapshot()
        assert after["evidence"] == before["evidence"] == []
        for card in after["peers"].values():
            assert card["fork_redeliveries"] == 0
            assert card["truncation_redeliveries"] == 0


class TestWatchdog:
    def test_silent_peer_goes_stale_and_suspect(self):
        monitor = fresh_monitor(stale_after=30.0)
        engine = make_engine(monitor)
        pid = engine.create_proposal("s", make_request(4), NOW).proposal_id
        voter = StubConsensusSigner(b"\x07" * 20)
        vote = build_vote(engine.get_proposal("s", pid), True, voter, NOW + 1)
        engine.ingest_votes([("s", vote)], NOW + 1)
        assert monitor.watchdog(NOW + 10) == []
        stale = monitor.watchdog(NOW + 50_000)
        assert voter.identity().hex() in stale
        monitor.tick(NOW + 50_000)
        card = monitor.scorecard(voter.identity())
        assert card["stale"] and card["grade"] == GRADE_SUSPECT

    def test_session_timeout_config_raises_threshold(self):
        """A peer voting on long-timeout sessions is not stale until the
        scope's own timeout has passed — 'the scope's timeout config'."""
        monitor = fresh_monitor(stale_after=10.0)
        monitor.note_admitted({b"\x01" * 20: 1}, NOW, timeout_hint=500.0)
        assert monitor.watchdog(NOW + 100) == []  # inside the hint
        assert monitor.watchdog(NOW + 600) == [(b"\x01" * 20).hex()]

    def test_timeout_calls_advance_the_watchdog_clock(self):
        monitor = fresh_monitor(stale_after=30.0)
        engine = make_engine(monitor)
        engine.create_proposal("s", make_request(4, expiry=100), NOW)
        monitor.note_admitted({b"\x01" * 20: 1}, NOW)
        engine.sweep_timeouts(NOW + 10_000)
        assert monitor.latest_now == NOW + 10_000
        assert monitor.watchdog() == [(b"\x01" * 20).hex()]


class TestAlertRules:
    def test_critical_rule_fires_on_equivocation(self):
        monitor = fresh_monitor()
        monitor.note_equivocation("s", 1, b"\x01", b"\x02", b"\x07" * 20, NOW)
        firing = monitor.evaluate_alerts(NOW)
        assert any(
            a["rule"] == "peer-faulty" and a["severity"] == "critical"
            for a in firing
        )

    def test_alert_events_are_edge_triggered(self):
        reg = MetricsRegistry()
        monitor = fresh_monitor(registry=reg)
        monitor.note_equivocation("s", 1, b"\x01", b"\x02", b"\x07" * 20, NOW)
        for _ in range(5):  # a /healthz poll loop
            assert monitor.evaluate_alerts(NOW)
        assert reg.counter(ALERTS_TOTAL).value == 2  # faulty + suspect edges
        assert reg.counter(f'{ALERTS_TOTAL}{{rule="peer-faulty"}}').value == 1

    def test_custom_counter_rule(self):
        reg = MetricsRegistry()
        monitor = fresh_monitor(registry=reg, rules=[])
        monitor.add_rule(
            AlertRule.counter_above("too-many-boops", "boops_total", 3)
        )
        assert monitor.evaluate_alerts(NOW) == []
        reg.counter("boops_total").inc(10)
        [alert] = monitor.evaluate_alerts(NOW)
        assert alert["rule"] == "too-many-boops"
        assert alert["details"][0]["value"] == 10

    def test_broken_rule_does_not_poison_evaluation(self):
        monitor = fresh_monitor(rules=[])
        monitor.add_rule(AlertRule("boom", lambda view: 1 / 0))
        monitor.add_rule(
            AlertRule("always", lambda view: [{"hit": True}])
        )
        [alert] = monitor.evaluate_alerts(NOW)
        assert alert["rule"] == "always"

    def test_labelled_alert_counter_renders_in_prometheus(self):
        reg = MetricsRegistry()
        monitor = fresh_monitor(registry=reg, rules=[])
        monitor.add_rule(AlertRule("always", lambda view: [{}]))
        monitor.evaluate_alerts(NOW)
        text = reg.render_prometheus()
        assert 'hashgraph_alerts_total{rule="always"} 1' in text
        # One TYPE line for the family, bare sample adjacent.
        assert text.count("# TYPE hashgraph_alerts_total counter") == 1

    def test_quoted_rule_name_cannot_corrupt_the_scrape(self):
        """A rule name containing quotes/backslashes must be label-escaped
        in the per-rule counter — one bad name would otherwise invalidate
        the ENTIRE Prometheus exposition (review finding)."""
        reg = MetricsRegistry()
        monitor = fresh_monitor(registry=reg, rules=[])
        monitor.add_rule(AlertRule('lag > "5s"', lambda view: [{}]))
        monitor.evaluate_alerts(NOW)
        text = reg.render_prometheus()
        assert 'hashgraph_alerts_total{rule="lag > \\"5s\\""} 1' in text


class TestEvidenceBounds:
    def test_evidence_log_is_bounded(self):
        monitor = fresh_monitor(max_evidence=3)
        for i in range(10):
            monitor.note_equivocation(
                "s", i, bytes([i]), bytes([i, i]), b"\x07" * 20, NOW + i
            )
        assert monitor.evidence_count() == 3
        kept = {r["proposal_id"] for r in monitor.evidence()}
        assert kept == {7, 8, 9}


class TestGaugeRegistration:
    def test_register_gauges_is_idempotent_per_registry(self):
        """Providers are additive across registrations: a monitor handed
        to a BridgeServer after being registered elsewhere must not
        double its gauge contributions (review finding)."""
        from hashgraph_tpu.obs.health import TRACKED_PEERS

        reg = MetricsRegistry()
        monitor = HealthMonitor(registry=reg)
        monitor.register_gauges(reg)
        monitor.register_gauges(reg)
        monitor.note_admitted({b"\x01" * 20: 1}, NOW)
        assert reg.gauge(TRACKED_PEERS).value == 1

    def test_server_does_not_reregister_passed_monitor(self):
        from hashgraph_tpu.obs.health import TRACKED_PEERS

        reg = MetricsRegistry()
        monitor = HealthMonitor(registry=reg)
        monitor.register_gauges(reg)
        server = BridgeServer(
            capacity=8, voter_capacity=8, health_monitor=monitor
        )
        assert server._health_monitor is monitor
        monitor.note_admitted({b"\x01" * 20: 1}, NOW)
        assert reg.gauge(TRACKED_PEERS).value == 1


class TestHealthReportSurfaces:
    def test_engine_health_report_shape(self):
        engine = make_engine()
        report = engine.health_report(NOW)
        assert set(report) >= {
            "now",
            "peers",
            "evidence",
            "watchdog",
            "alerts",
            "identity",
        }
        json.dumps(report)  # must be JSON-serializable as-is

    def test_durable_overlay(self, tmp_path):
        from hashgraph_tpu import DurableEngine

        durable = DurableEngine(
            make_engine(), str(tmp_path / "wal"), fsync_policy="off"
        )
        durable.create_proposal("s", make_request(4), NOW)
        report = durable.health_report(NOW)
        assert report["wal"]["last_lsn"] == 1
        assert report["wal"]["fsync_policy"] == "off"
        durable.close()

    def test_replay_does_not_double_count(self, tmp_path):
        """WAL recovery replays the equivocating delivery; the monitor
        must not re-score it (the anomaly predates the crash)."""
        from hashgraph_tpu import DurableEngine

        monitor = fresh_monitor()
        durable = DurableEngine(
            make_engine(monitor), str(tmp_path / "wal"), fsync_policy="off"
        )
        pid = durable.create_proposal("s", make_request(6), NOW).proposal_id
        signer = StubConsensusSigner(b"\x07" * 20)
        v1 = build_vote(durable.get_proposal("s", pid), True, signer, NOW + 1)
        durable.ingest_votes([("s", v1)], NOW + 1)
        v2 = build_vote(durable.get_proposal("s", pid), False, signer, NOW + 2)
        durable.ingest_votes([("s", v2)], NOW + 2)
        assert monitor.scorecard(signer.identity())["equivocations"] == 1
        durable.close()

        monitor2 = fresh_monitor()
        restarted = DurableEngine(
            make_engine(monitor2), str(tmp_path / "wal"), fsync_policy="off"
        )
        restarted.recover()
        assert restarted.get_proposal("s", pid) is not None
        card = monitor2.scorecard(signer.identity())
        assert card is None or card["equivocations"] == 0
        restarted.close()


class TestBridgeHealth:
    def test_op_health_round_trip(self):
        monitor = fresh_monitor()
        with BridgeServer(
            capacity=16, voter_capacity=8, health_monitor=monitor
        ) as server:
            with BridgeClient(*server.address) as client:
                peer, identity = client.add_peer()
                pid, _ = client.create_proposal(
                    peer, "h", NOW, "p", b"", 2, 100
                )
                client.cast_vote(peer, "h", pid, True, NOW + 1)
                report = client.health(peer, NOW + 2)
                assert report["identity"] == identity.hex()
                card = report["peers"][identity.hex()]
                assert card["votes_admitted"] == 1
                assert card["grade"] == GRADE_HEALTHY
                assert report["alerts"]["firing"] == []

    def test_equivocation_and_fork_retrievable_over_the_wire(self):
        """Acceptance: an equivocating peer AND a fork redelivery each
        produce a retrievable self-authenticating evidence record via
        BridgeClient.health()."""
        monitor = fresh_monitor()
        with BridgeServer(
            capacity=16, voter_capacity=8, health_monitor=monitor
        ) as server:
            with BridgeClient(*server.address) as client:
                peer, _ = client.add_peer()
                pid, proposal_bytes = client.create_proposal(
                    peer, "h", NOW, "p", b"", 8, 10_000
                )
                from hashgraph_tpu import EthereumConsensusSigner
                from hashgraph_tpu.wire import Proposal

                # Equivocation through the wire vote path (the bridge's
                # peer engines verify with the Ethereum scheme).
                signer = EthereumConsensusSigner.random()
                view = Proposal.decode(
                    client.get_proposal(peer, "h", pid)
                )
                v1 = build_vote(view, True, signer, NOW + 1)
                client.process_vote(peer, "h", v1.encode(), NOW + 1)
                view = Proposal.decode(client.get_proposal(peer, "h", pid))
                v2 = build_vote(view, False, signer, NOW + 2)
                with pytest.raises(Exception):
                    client.process_vote(peer, "h", v2.encode(), NOW + 2)
                # Fork: a redelivered chain in which the signer's OWN
                # accepted vote is replaced by a different vote it signed
                # (the double-sign bar — a divergence at another owner's
                # position is honestly producible and records nothing),
                # driven through the peer engine's deliver_proposal (the
                # gossip-facing surface).
                honest = Proposal.decode(client.get_proposal(peer, "h", pid))
                forked_long = honest.clone()
                forked_long.votes = [
                    build_vote(
                        Proposal.decode(proposal_bytes), False, signer, NOW + 4
                    )
                ] + [v.clone() for v in honest.votes]
                engine = server._peers[peer].engine
                assert engine.deliver_proposal(
                    "h", forked_long, NOW + 5
                ) == int(StatusCode.PROPOSAL_ALREADY_EXIST)

                report = client.health(peer, NOW + 6)
                kinds = {r["kind"] for r in report["evidence"]}
                assert kinds == {KIND_EQUIVOCATION, KIND_FORK}
                equiv = next(
                    r
                    for r in report["evidence"]
                    if r["kind"] == KIND_EQUIVOCATION
                )
                # Self-authenticating: both sides verify offline with
                # real ECDSA recovery, no trust in the server needed.
                for key in ("vote_a", "vote_b"):
                    vote = Vote.decode(bytes.fromhex(equiv[key]))
                    assert EthereumConsensusSigner.verify(
                        vote.vote_owner, vote.signing_payload(), vote.signature
                    )

    def test_critical_alert_flips_healthz_to_503(self):
        """Acceptance: a triggered alert rule flips /healthz to 503 with
        a machine-readable reason."""
        monitor = fresh_monitor()
        with BridgeServer(
            capacity=16,
            voter_capacity=8,
            metrics_port=0,
            health_monitor=monitor,
        ) as server:
            host, port = server.metrics_address
            with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=5
            ) as response:
                body = json.loads(response.read())
            assert body["ok"] is True and body["alerts"] == []

            monitor.note_equivocation(
                "s", 1, b"\x01", b"\x02", b"\x07" * 20, NOW
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5
                )
            assert err.value.code == 503
            degraded = json.loads(err.value.read())
            assert degraded["ok"] is False
            [reason] = [
                r for r in degraded["reasons"] if r["rule"] == "peer-faulty"
            ]
            assert reason["severity"] == "critical"
            assert reason["details"][0]["peer"] == (b"\x07" * 20).hex()


class TestConcurrentScorecards:
    def test_concurrent_ingest_accounting_is_exact(self):
        """N threads hammer ingest_votes on one engine: the scorecard
        totals must equal the sequential truth (one admission per
        accepted vote, no lost updates)."""
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        engine.scope("s").with_threshold(1.0).initialize()
        pid = engine.create_proposal("s", make_request(16), NOW).proposal_id
        base = engine.get_proposal("s", pid)
        voters = [random_stub_signer() for _ in range(12)]
        votes = [build_vote(base, True, s, NOW + 1) for s in voters]
        barrier = threading.Barrier(len(votes))
        statuses = []
        lock = threading.Lock()

        def worker(vote):
            barrier.wait()
            st = engine.ingest_votes([("s", vote)], NOW + 1)
            with lock:
                statuses.append(int(st[0]))

        threads = [threading.Thread(target=worker, args=(v,)) for v in votes]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert statuses.count(OK) == len(votes)
        total = sum(
            monitor.scorecard(s.identity())["votes_admitted"] for s in voters
        )
        assert total == len(votes)
        for signer in voters:
            assert monitor.scorecard(signer.identity())["grade"] == GRADE_HEALTHY

    def test_concurrent_snapshot_during_ingest(self):
        """Scrape-thread snapshots race live ingest without deadlock or
        exception (the monitor has its own lock, never the engine's)."""
        monitor = fresh_monitor()
        engine = make_engine(monitor)
        engine.scope("s").with_threshold(1.0).initialize()
        pid = engine.create_proposal("s", make_request(64), NOW).proposal_id
        base = engine.get_proposal("s", pid)
        votes = [
            build_vote(base, True, random_stub_signer(), NOW + 1)
            for _ in range(16)
        ]
        stop = threading.Event()
        failures = []

        def scraper():
            while not stop.is_set():
                try:
                    snap = engine.health_report(NOW + 1)
                    json.dumps(snap)
                except Exception as exc:  # pragma: no cover
                    failures.append(exc)
                    return

        thread = threading.Thread(target=scraper)
        thread.start()
        for vote in votes:
            engine.ingest_votes([("s", vote)], NOW + 1)
        stop.set()
        thread.join()
        assert not failures
        assert monitor.peer_count() == 16
