"""Property-based fuzz: pipelined ingest vs sequential as oracle
(hypothesis drives the script space beyond test_pipelined_ingest.py's
hand-written cases)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from hashgraph_tpu import build_vote

from common import NOW
from test_pipelined_ingest import (
    N_SIGNERS,
    SIGNERS,
    _fresh_engine,
    _req,
    _state_fingerprint,
)

# One op per entry: (proposal index, signer index, kind) where kind
# selects a clean vote, a corrupted signature, a duplicate, or a vote
# for an unknown session.
op_lists = st.lists(
    st.tuples(
        st.integers(min_value=0, max_value=2),
        st.integers(min_value=0, max_value=N_SIGNERS - 1),
        st.sampled_from(["ok", "bad_sig", "dup", "unknown"]),
    ),
    min_size=1,
    max_size=24,
)


@settings(max_examples=25, deadline=None)
@given(ops=op_lists, batch_size=st.integers(min_value=1, max_value=7))
def test_property_pipelined_equals_sequential(ops, batch_size):
    """For ANY vote script and batching, pipelined == sequential:
    statuses, stored chains, and per-session vote maps."""
    seq = _fresh_engine()
    pip = _fresh_engine()
    fingerprints = []
    outs = []
    for engine in (seq, pip):
        proposals = [
            engine.create_proposal("s", _req(), NOW) for _ in range(3)
        ]
        items = []
        last = {}
        for p_idx, s_idx, kind in ops:
            proposal = proposals[p_idx]
            if kind == "dup" and (p_idx, s_idx) in last:
                items.append(("s", last[(p_idx, s_idx)].clone()))
                continue
            vote = build_vote(
                proposal, bool(s_idx % 2), SIGNERS[s_idx], NOW + 1 + s_idx
            )
            if kind == "bad_sig":
                vote.signature = bytes([vote.signature[0] ^ 1]) + vote.signature[1:]
            elif kind == "unknown":
                vote.proposal_id = 777_000 + p_idx
            else:
                last[(p_idx, s_idx)] = vote
            items.append(("s", vote))
        batches = [
            items[k : k + batch_size] for k in range(0, len(items), batch_size)
        ]
        if engine is seq:
            outs.append([engine.ingest_votes(b, NOW) for b in batches])
        else:
            outs.append(engine.ingest_votes_pipelined(batches, NOW))
        fingerprints.append(
            _state_fingerprint(engine, "s", [p.proposal_id for p in proposals])
        )
    for a, b in zip(outs[0], outs[1]):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert fingerprints[0] == fingerprints[1]
