"""Device-resident Ed25519 batch verification: the correctness battery.

Four layers, each pinned against an independent oracle:

- the u32-limb field core against Python big-int arithmetic (random,
  boundary, AND adversarial near-0xFFFF ripple patterns — the carry
  chain's rigor claim is load-bearing for soundness);
- vectorized SHA-512 against hashlib;
- curve ops + batched decompression against the pure-Python RFC 8032
  twin (``signing/_ed25519.py``), including every 5.1.3 rejection class;
- the seam (``Ed25519DeviceConsensusSigner``) against BOTH host
  verifiers — the pure-Python twin per item and the native pool's batch
  path — on RFC 8032 vectors, a seeded fuzz corpus (non-canonical
  encodings, s >= L, low-order points, corrupted signatures, ragged
  batches), and the exact-per-item-blame contract.

Shape discipline: small-batch tests share ONE set of lane/block buckets
(n <= 6 -> 16-lane MSM) so tier-1 pays each XLA compile once. The
4k-batch blame case and the chaos scenarios are ``slow``-marked: tier-1
skips them, the ``device-crypto`` CI job (and ``pytest -m slow``) runs
them.
"""

import hashlib
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hashgraph_tpu import native  # noqa: E402
from hashgraph_tpu.crypto_device import curve, msm  # noqa: E402
from hashgraph_tpu.crypto_device import field as fe  # noqa: E402
from hashgraph_tpu.crypto_device import sha512 as sh  # noqa: E402
from hashgraph_tpu.errors import ConsensusSchemeError  # noqa: E402
from hashgraph_tpu.obs import (  # noqa: E402
    DEVICE_VERIFY_BATCHES_TOTAL,
    DEVICE_VERIFY_FALLBACKS_TOTAL,
    DEVICE_VERIFY_SECONDS,
    DEVICE_VERIFY_SIGNATURES_TOTAL,
    registry,
)
from hashgraph_tpu.signing import (  # noqa: E402
    Ed25519ConsensusSigner,
    Ed25519DeviceConsensusSigner,
)
from hashgraph_tpu.signing import _ed25519 as py  # noqa: E402

P = fe.P
L = py.L


def _limbs(vals):
    return jnp.asarray(
        np.array(
            [[(v >> (16 * j)) & 0xFFFF for j in range(16)] for v in vals],
            np.uint32,
        )
    )


def _pt_limbs(pt):
    return np.array(
        [[(v >> (16 * b)) & 0xFFFF for b in range(16)] for v in pt],
        np.uint32,
    )


def _carried(arr) -> bool:
    return bool((np.asarray(arr) < (1 << 16)).all())


class TestFieldCore:
    def test_mul_add_sub_vs_python_ints(self):
        rng = random.Random(0xFE1D)
        vals_a = [rng.getrandbits(256) for _ in range(48)]
        vals_b = [rng.getrandbits(256) for _ in range(48)]
        # Boundaries + adversarial ripple patterns: all-0xFFFF limbs,
        # p itself, 2p, values crafted so carries cascade end to end.
        vals_a += [0, 1, 19, P - 1, P, P + 1, 2 * P, 2**256 - 1,
                   2**256 - 2**240, (2**256 - 2**240) | 0xFFFF]
        vals_b += [2**256 - 1, 2**256 - 1, 2**256 - 1, 1, 0, P, 1,
                   2**256 - 1, 1, 1]
        a, b = _limbs(vals_a), _limbs(vals_b)
        got_mul = np.asarray(fe.mul(a, b))
        got_add = np.asarray(fe.add(a, b))
        got_sub = np.asarray(fe.sub(a, b))
        for i, (x, y) in enumerate(zip(vals_a, vals_b)):
            assert fe.limbs_to_int(got_mul[i]) % P == (x * y) % P
            assert fe.limbs_to_int(got_add[i]) % P == (x + y) % P
            assert fe.limbs_to_int(got_sub[i]) % P == (x - y) % P
        # The carried invariant is soundness-critical: a limb at 2^16
        # would square to 2^32 === 0 in uint32 and verify garbage.
        assert _carried(got_mul) and _carried(got_add) and _carried(got_sub)

    def test_exponentiation_chains(self):
        rng = random.Random(0xCA1)
        vals = [rng.getrandbits(255) for _ in range(8)] + [1, 2, P - 1]
        a = _limbs(vals)
        inv = np.asarray(fe.invert(a))
        p22 = np.asarray(fe.pow22523(a))
        for i, v in enumerate(vals):
            assert fe.limbs_to_int(inv[i]) % P == pow(v % P, P - 2, P)
            assert fe.limbs_to_int(p22[i]) % P == pow(v % P, (P - 5) // 8, P)

    def test_canon_and_bytes(self):
        vals = [0, 1, P - 1, P, P + 1, 2 * P + 5, 2**256 - 1]
        a = _limbs(vals)
        can = np.asarray(fe.canon(a))
        enc = np.asarray(fe.to_bytes(a))
        for i, v in enumerate(vals):
            assert fe.limbs_to_int(can[i]) == v % P
            assert int.from_bytes(enc[i].tobytes(), "little") == v % P
        # Canonical-encoding flags: y < p accepted, y >= p rejected.
        flags = np.asarray(fe.is_canonical_fe(jnp.asarray(np.stack([
            np.frombuffer((P - 1).to_bytes(32, "little"), np.uint8),
            np.frombuffer(P.to_bytes(32, "little"), np.uint8),
            np.frombuffer((2**255 - 1).to_bytes(32, "little"), np.uint8),
        ]))))
        assert flags.tolist() == [True, False, False]


class TestSha512Device:
    def test_against_hashlib_ragged_single_dispatch(self):
        rng = random.Random(5)
        msgs = [b"", b"abc", b"a" * 111, b"b" * 112, b"c" * 127,
                b"d" * 128, b"e" * 129, b"f" * 255,
                bytes(rng.randrange(256) for _ in range(217))]
        out = sh.sha512_batch(msgs, 4)
        for m, d in zip(msgs, out):
            assert d.tobytes() == hashlib.sha512(m).digest(), len(m)

    def test_derived_constants_match_fips(self):
        # Spot-pin the derived K/H against the published first/last
        # values so a broken integer-root can't quietly pass (the
        # hashlib comparison above would catch it too — two oracles).
        assert sh._K64[0] == 0x428A2F98D728AE22
        assert sh._K64[79] == 0x6C44198C4A475817
        assert sh._H64[0] == 0x6A09E667F3BCC908
        assert sh._H64[7] == 0x5BE0CD19137E2179


class TestCurveDevice:
    def test_decompress_parity_with_host_twin(self):
        rng = random.Random(9)
        encs = []
        for _ in range(8):
            encs.append(py._encode(py._mul(py._BASE, rng.getrandbits(252))))
        encs += [
            b"\x01" + b"\x00" * 31,               # identity (y=1)
            bytes(32),                             # y=0 (order-4 point)
            b"\xff" * 32,                          # y >= p: non-canonical
            py.P.to_bytes(32, "little"),           # y = p: non-canonical
            (py.P - 1).to_bytes(32, "little"),     # may lack a root
            b"\x02" + b"\x00" * 31,
            bytes(31) + b"\x80",                   # x=0 with sign bit
            b"\x03" + b"\x00" * 30 + b"\x80",
        ]
        arr = jnp.asarray(
            np.frombuffer(b"".join(encs), np.uint8).reshape(-1, 32)
        )
        pts, ok = curve.decompress(arr)
        pts, ok = np.asarray(pts), np.asarray(ok)
        for i, enc in enumerate(encs):
            want = py._decode(enc)
            assert bool(ok[i]) == (want is not None), enc.hex()
            if want is None:
                continue
            x, y, z, _ = want
            zi = pow(z, P - 2, P)
            for coord, host in ((0, x * zi % P), (1, y * zi % P)):
                got = fe.limbs_to_int(
                    np.asarray(fe.canon(jnp.asarray(pts[i][coord])))
                )
                assert got == host, (i, coord)

    def test_add_dbl_parity_with_host_twin(self):
        rng = random.Random(11)
        host_pts = [
            py._mul(py._BASE, rng.getrandbits(250)) for _ in range(4)
        ] + [py._IDENTITY]
        arr = jnp.asarray(np.stack([_pt_limbs(p) for p in host_pts]))
        got_dbl = np.asarray(curve.dbl(arr))
        got_add = np.asarray(curve.add(arr, arr[::-1].copy()))

        def affine(pt):
            x, y, z, _ = pt
            zi = pow(z, P - 2, P)
            return (x * zi % P, y * zi % P)

        def affine_dev(row):
            x, y, z = (
                fe.limbs_to_int(np.asarray(fe.canon(jnp.asarray(row[j]))))
                for j in range(3)
            )
            zi = pow(z, P - 2, P)
            return (x * zi % P, y * zi % P)

        for i, p in enumerate(host_pts):
            assert affine_dev(got_dbl[i]) == affine(py._dbl(p))
            assert affine_dev(got_add[i]) == affine(
                py._add(p, host_pts[len(host_pts) - 1 - i])
            )

    def test_msm_identity_criterion(self):
        # s*P + (L-s)*P cancels (mod the cofactor the final *8 clears).
        rng = random.Random(13)
        pt = py._decode(py._encode(py._mul(py._BASE, rng.getrandbits(250))))
        pts = np.broadcast_to(curve.IDENTITY, (8, 4, 16)).copy()
        pts[0] = pts[1] = _pt_limbs(pt)
        s = rng.getrandbits(251) % L
        nib = np.zeros((8, 64), np.int32)
        nib[:2] = msm.scalars_to_nibbles([s, L - s])
        assert msm.msm_accepts(jnp.asarray(pts), jnp.asarray(nib))
        nib[0, 63] ^= 1
        assert not msm.msm_accepts(jnp.asarray(pts), jnp.asarray(nib))


# ── The seam: RFC 8032 vectors + decision-identity vs host verifiers ──

RFC8032_VECTORS = [
    # (seed hex, public hex, message hex, signature hex) — RFC 8032 §7.1
    ("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
     "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
     "",
     "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
     "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"),
    ("4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
     "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
     "72",
     "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
     "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"),
    ("c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
     "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
     "af82",
     "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
     "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"),
]


def _device_batch(idents, payloads, sigs):
    return Ed25519DeviceConsensusSigner.verify_batch(idents, payloads, sigs)


def _host_expected(idents, payloads, sigs):
    """The oracle: per-item pure-Python RFC 8032 verdicts, with the
    seam's length-error convention layered on."""
    out = []
    for ident, payload, sig in zip(idents, payloads, sigs):
        if len(sig) != 64 or len(ident) != 32:
            out.append("scheme-error")
        else:
            out.append(py.verify(bytes(ident), payload, bytes(sig)))
    return out


def _assert_decision_identical(idents, payloads, sigs):
    got = _device_batch(idents, payloads, sigs)
    want = _host_expected(idents, payloads, sigs)
    native_got = Ed25519ConsensusSigner.verify_batch(idents, payloads, sigs)
    assert len(got) == len(want) == len(native_got)
    for g, n, w in zip(got, native_got, want):
        if w == "scheme-error":
            assert isinstance(g, ConsensusSchemeError)
            assert isinstance(n, ConsensusSchemeError)
        else:
            assert g is w, (g, w)
            assert n is w, (n, w)


class TestDeviceSeam:
    def test_rfc8032_vectors_pinned(self):
        idents, payloads, sigs = [], [], []
        for seed_hex, pub_hex, msg_hex, sig_hex in RFC8032_VECTORS:
            signer = Ed25519ConsensusSigner(
                bytes.fromhex(seed_hex), device_verify=True
            )
            assert type(signer) is Ed25519DeviceConsensusSigner
            assert signer.identity().hex() == pub_hex
            msg = bytes.fromhex(msg_hex)
            sig = signer.sign(msg)
            assert sig.hex() == sig_hex
            idents.append(signer.identity())
            payloads.append(msg)
            sigs.append(sig)
        assert _device_batch(idents, payloads, sigs) == [True] * 3
        # Any single-bit corruption must flip exactly that verdict.
        bad = list(sigs)
        bad[1] = bytes([bad[1][0] ^ 1]) + bad[1][1:]
        assert _device_batch(idents, payloads, bad) == [True, False, True]

    def test_selection_seam(self, monkeypatch):
        seed = b"\x42" * 32
        assert type(Ed25519ConsensusSigner(seed)) is Ed25519ConsensusSigner
        dev = Ed25519ConsensusSigner(seed, device_verify=True)
        assert type(dev) is Ed25519DeviceConsensusSigner
        assert dev.identity() == Ed25519ConsensusSigner(seed).identity()
        monkeypatch.setenv("HASHGRAPH_TPU_DEVICE_VERIFY", "1")
        assert type(Ed25519ConsensusSigner(seed)) is (
            Ed25519DeviceConsensusSigner
        )
        # Explicit False beats the env; subclass construction sticks.
        assert type(
            Ed25519ConsensusSigner(seed, device_verify=False)
        ) is Ed25519ConsensusSigner
        assert type(Ed25519DeviceConsensusSigner.random()) is (
            Ed25519DeviceConsensusSigner
        )
        monkeypatch.setenv("HASHGRAPH_TPU_DEVICE_VERIFY", "0")
        assert type(Ed25519ConsensusSigner(seed)) is Ed25519ConsensusSigner

    def test_seeded_fuzz_decision_identity(self):
        """Every mutation class the wire can produce, device == host,
        at ONE lane bucket (n=6) so the compile is paid once."""
        rng = random.Random(0xF0D5)
        signers = [Ed25519DeviceConsensusSigner.random() for _ in range(3)]
        low_order = [b"\x01" + b"\x00" * 31, bytes(32),
                     b"\xec" + b"\xff" * 30 + b"\x7f"]  # y = p-3... reject/ok per twin
        for round_no in range(6):
            idents, payloads, sigs = [], [], []
            for i in range(6):
                s = signers[rng.randrange(3)]
                payload = b"fuzz-%d-%d" % (round_no, i)
                ident, sig = s.identity(), s.sign(payload)
                mutation = rng.randrange(8)
                if mutation == 1:
                    sig = bytes([sig[0] ^ (1 << rng.randrange(8))]) + sig[1:]
                elif mutation == 2:  # corrupt s, keep it canonical
                    s_int = int.from_bytes(sig[32:], "little")
                    s_int = (s_int + 1 + rng.getrandbits(100)) % L
                    sig = sig[:32] + s_int.to_bytes(32, "little")
                elif mutation == 3:  # non-canonical scalar s + L
                    s_int = int.from_bytes(sig[32:], "little")
                    if s_int + L < 2**256:
                        sig = sig[:32] + (s_int + L).to_bytes(32, "little")
                elif mutation == 4:  # undecodable / non-canonical A
                    ident = rng.choice([b"\xff" * 32, py.P.to_bytes(32, "little")])
                elif mutation == 5:  # low-order or identity R
                    sig = rng.choice(low_order) + sig[32:]
                elif mutation == 6:  # cross-wired payload
                    payload = b"someone-else's-bytes"
                elif mutation == 7:  # low-order A
                    ident = rng.choice(low_order)
                idents.append(ident)
                payloads.append(payload)
                sigs.append(sig)
            _assert_decision_identical(idents, payloads, sigs)

    def test_ragged_scheme_errors_empty(self):
        s = Ed25519DeviceConsensusSigner.random()
        sig = s.sign(b"p")
        out = _device_batch(
            [s.identity(), b"\x01" * 5, s.identity()],
            [b"p", b"p", b"p"],
            [sig, sig, b"xx"],
        )
        assert out[0] is True
        assert isinstance(out[1], ConsensusSchemeError)
        assert isinstance(out[2], ConsensusSchemeError)
        assert len(
            Ed25519DeviceConsensusSigner.verify_batch(
                [s.identity()] * 4, [b"p"] * 2, [sig] * 4
            )
        ) == 2
        assert Ed25519DeviceConsensusSigner.verify_batch([], [], []) == []

    def test_submit_collect_and_metrics(self):
        batches0 = registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value
        sigs0 = registry.counter(DEVICE_VERIFY_SIGNATURES_TOTAL).value
        hist0 = registry.histogram(DEVICE_VERIFY_SECONDS).count
        signers = [Ed25519DeviceConsensusSigner.random() for _ in range(3)]
        payloads = [b"m-%d" % i for i in range(6)]
        idents = [signers[i % 3].identity() for i in range(6)]
        sigs = [signers[i % 3].sign(p) for i, p in enumerate(payloads)]
        pend = Ed25519DeviceConsensusSigner.verify_batch_submit(
            idents, payloads, sigs
        )
        got = pend.collect()
        assert got == [True] * 6
        assert pend.collect() is got  # idempotent
        assert registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value == (
            batches0 + 1
        )
        assert registry.counter(DEVICE_VERIFY_SIGNATURES_TOTAL).value == (
            sigs0 + 6
        )
        assert registry.histogram(DEVICE_VERIFY_SECONDS).count == hist0 + 1
        phases = Ed25519DeviceConsensusSigner.device_phase_seconds()
        assert set(phases) >= {"decompress", "hash", "msm", "total"}

    def test_blame_fallback_exact_and_counted(self):
        """A wrong-but-well-encoded signature survives decompression, so
        the linear combination itself must fail and the host blame pass
        must name exactly the bad row (and count the escalation)."""
        fb0 = registry.counter(DEVICE_VERIFY_FALLBACKS_TOTAL).value
        signers = [Ed25519DeviceConsensusSigner.random() for _ in range(3)]
        payloads = [b"blame-%d" % i for i in range(6)]
        idents = [signers[i % 3].identity() for i in range(6)]
        sigs = [signers[i % 3].sign(p) for i, p in enumerate(payloads)]
        # Tamper with s only (stays canonical, R still decodes): the
        # only rejection path left is the batch equation.
        s_int = int.from_bytes(sigs[4][32:], "little")
        sigs[4] = sigs[4][:32] + ((s_int + 7) % L).to_bytes(32, "little")
        out = _device_batch(idents, payloads, sigs)
        assert out == [True, True, True, True, False, True]
        assert registry.counter(DEVICE_VERIFY_FALLBACKS_TOTAL).value == (
            fb0 + 1
        )
        phases = Ed25519DeviceConsensusSigner.device_phase_seconds()
        assert phases["fallback"] > 0.0

    def test_engine_reaches_device_path_through_seam(self):
        """End to end: an engine built with a device signer runs its
        verify prepass on the backend with ZERO engine changes, and the
        per-scheme counter picks up the distinct backend label."""
        from hashgraph_tpu.engine import TpuConsensusEngine
        from hashgraph_tpu.obs import VERIFIED_SIGNATURES_TOTAL
        from hashgraph_tpu.protocol import compute_vote_hash
        from hashgraph_tpu.types import CreateProposalRequest
        from hashgraph_tpu.wire import Vote

        batches0 = registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value
        labelled = registry.counter(
            VERIFIED_SIGNATURES_TOTAL
            + '{scheme="Ed25519DeviceConsensusSigner"}'
        )
        labelled0 = labelled.value
        engine = TpuConsensusEngine(
            Ed25519DeviceConsensusSigner.random(),
            capacity=8,
            voter_capacity=4,
        )
        now = 1_700_000_000
        scope = "device-seam"
        voters = [Ed25519DeviceConsensusSigner.random() for _ in range(3)]
        proposal = engine.create_proposals(
            scope,
            [CreateProposalRequest(
                name="p", payload=b"", proposal_owner=b"o",
                expected_voters_count=3, expiration_timestamp=now + 100,
                liveness_criteria_yes=True,
            )],
            now,
        )[0]
        votes = []
        for lane, voter in enumerate(voters):
            vote = Vote(
                vote_id=lane + 1, vote_owner=voter.identity(),
                proposal_id=proposal.proposal_id, timestamp=now,
                vote=True, parent_hash=b"", received_hash=b"",
                vote_hash=b"", signature=b"",
            )
            vote.vote_hash = compute_vote_hash(vote)
            vote.signature = voter.sign(vote.signing_payload())
            votes.append(vote)
        # Corrupt the last vote's signature scalar: the device batch
        # must blame exactly it while admitting the other two.
        s_int = int.from_bytes(votes[2].signature[32:], "little")
        votes[2].signature = votes[2].signature[:32] + (
            (s_int + 3) % L
        ).to_bytes(32, "little")
        statuses = engine.ingest_votes([(scope, v) for v in votes], now)
        assert [int(code) for code in statuses[:2]] == [0, 0]
        assert int(statuses[2]) != 0
        assert registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value > batches0
        assert labelled.value > labelled0
        engine.delete_scope(scope)


@pytest.mark.skipif(not native.available(), reason="native runtime absent")
class TestNativeParity:
    def test_device_vs_native_pool_mixed_verdicts(self):
        """The two batch backends (device RLC + native pool RLC) must
        agree verdict-for-verdict on a mixed batch — the native-parity
        contract of PARITY.md's 'Device-resident verification' row."""
        rng = random.Random(0xAB)
        signers = [Ed25519DeviceConsensusSigner.random() for _ in range(3)]
        payloads = [b"np-%d" % i for i in range(6)]
        idents = [signers[i % 3].identity() for i in range(6)]
        sigs = [signers[i % 3].sign(p) for i, p in enumerate(payloads)]
        for bad in (1, 4):
            s_int = int.from_bytes(sigs[bad][32:], "little")
            sigs[bad] = sigs[bad][:32] + (
                (s_int + rng.randrange(1, 99)) % L
            ).to_bytes(32, "little")
        device = _device_batch(idents, payloads, sigs)
        pool = native.ed25519_verify_batch(
            [bytes(i) for i in idents], payloads, [bytes(s) for s in sigs]
        )
        assert device == [code == 1 for code in pool]


@pytest.mark.slow
class TestBlame4k:
    def test_one_bad_signature_in_4096_names_exactly_that_index(self):
        rng = random.Random(0x4096)
        signers = [Ed25519DeviceConsensusSigner.random() for _ in range(8)]
        n, bad_index = 4096, 2026
        payloads = [b"batch4k-%04d" % i for i in range(n)]
        idents = [signers[i % 8].identity() for i in range(n)]
        sigs = [signers[i % 8].sign(p) for i, p in enumerate(payloads)]
        s_int = int.from_bytes(sigs[bad_index][32:], "little")
        sigs[bad_index] = sigs[bad_index][:32] + (
            (s_int + 1 + rng.getrandbits(64)) % L
        ).to_bytes(32, "little")
        fb0 = registry.counter(DEVICE_VERIFY_FALLBACKS_TOTAL).value
        out = _device_batch(idents, payloads, sigs)
        assert out[bad_index] is False
        assert all(
            verdict is True for i, verdict in enumerate(out) if i != bad_index
        )
        assert registry.counter(DEVICE_VERIFY_FALLBACKS_TOTAL).value == fb0 + 1


@pytest.mark.slow
class TestChaosWithDeviceBackend:
    """The deterministic chaos scenarios whose injectors attack
    signatures, re-run with the device backend forced on: all three
    machine-checked verdicts (convergence, exact-culprit accountability,
    safety) must hold unchanged — device-rejected rows mint the same
    scorecard attributions as host-rejected ones."""

    def _run(self, name, **kwargs):
        from hashgraph_tpu.sim.scenarios import run_scenario

        outcome = run_scenario(name, 1, **kwargs)
        assert outcome["passed"], outcome["checks"]
        for key, verdict in outcome["verdicts"].items():
            assert verdict["ok"], (name, key, verdict)
        return outcome

    def test_signature_burst_device_backend(self):
        self._run(
            "expired-spam-burst",
            signer_factory=Ed25519DeviceConsensusSigner,
        )

    def test_columnar_wire_storm_device_backend(self):
        self._run(
            "columnar-wire-storm",
            signer_factory=Ed25519DeviceConsensusSigner,
        )

    def test_signature_burst_env_selection(self, monkeypatch):
        """Same scenario, device backend selected by env alone — the
        zero-caller-change path a production deployment flips."""
        monkeypatch.setenv("HASHGRAPH_TPU_DEVICE_VERIFY", "1")
        batches0 = registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value
        self._run(
            "expired-spam-burst", signer_factory=Ed25519ConsensusSigner
        )
        assert registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).value > batches0


def test_hypothesis_fuzz_decision_identity():
    """Property-based mutation fuzz (skips cleanly without hypothesis,
    like the repo's other property suites)."""
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    signer = Ed25519DeviceConsensusSigner.random()
    good_sig = signer.sign(b"hyp")

    @hyp.settings(max_examples=12, deadline=None)
    @hyp.given(
        flips=st.lists(
            st.tuples(st.integers(0, 63), st.integers(0, 7)),
            min_size=0, max_size=3,
        ),
        ident_mut=st.sampled_from(
            ["keep", "ff", "p", "identity-point"]
        ),
    )
    def check(flips, ident_mut):
        sig = bytearray(good_sig)
        for pos, bit in flips:
            sig[pos] ^= 1 << bit
        ident = {
            "keep": signer.identity(),
            "ff": b"\xff" * 32,
            "p": py.P.to_bytes(32, "little"),
            "identity-point": b"\x01" + b"\x00" * 31,
        }[ident_mut]
        idents = [ident] * 6
        payloads = [b"hyp"] * 6
        sigs = [bytes(sig)] * 6
        _assert_decision_identical(idents, payloads, sigs)

    check()


@pytest.mark.parametrize("mode", ["interpret"])
def test_pallas_field_mul_interpret_parity(monkeypatch, mode):
    """The optional Pallas kernel, run through the interpreter (the
    only honest option off-TPU), must match the jnp field core."""
    from hashgraph_tpu.crypto_device import pallas_msm

    monkeypatch.setenv("HASHGRAPH_TPU_DEVICE_VERIFY_PALLAS", mode)
    pallas_msm.reset_for_tests()
    try:
        if not pallas_msm.enabled():
            pytest.skip("pallas interpreter unavailable on this backend")
        rng = random.Random(0x9A)
        vals_a = [rng.getrandbits(256) for _ in range(8)] + [2**256 - 1]
        vals_b = [rng.getrandbits(256) for _ in range(8)] + [2**256 - 1]
        a, b = _limbs(vals_a), _limbs(vals_b)
        got = np.asarray(pallas_msm.fe_mul(a, b))
        want = np.asarray(fe._mul_jnp(a, b))
        assert (got == want).all()
        assert _carried(got)
    finally:
        monkeypatch.delenv("HASHGRAPH_TPU_DEVICE_VERIFY_PALLAS")
        pallas_msm.reset_for_tests()
