"""Property tests for the φ-accrual core (hypothesis-gated).

Three laws the detector must hold for EVERY inter-arrival history, not
just the hand-picked ones in test_liveness.py:

1. phi is monotone non-decreasing in silence (a longer wait can only
   raise suspicion);
2. a heartbeat revises suspicion to zero instantly (Chandra–Toueg:
   suspicion may be wrong and must be cheap to revise);
3. at equal mean and equal silence, a history with wider spread never
   yields MORE suspicion than a tighter one (jitter earns tolerance).

The module skips cleanly where hypothesis is not installed (the repo
adds no dependencies); tests/test_liveness.py carries fixed-example
mirrors of each law so the properties are never entirely unexercised.
"""

import pytest

hypothesis = pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from hashgraph_tpu.obs.accrual import (  # noqa: E402
    DEFAULT_MAX_PHI,
    PhiAccrual,
    phi_from_deviation,
)

# Inter-arrival histories: enough samples to clear the min_samples gate,
# intervals wide enough apart that float noise cannot flip an ordering.
intervals = st.lists(
    st.floats(min_value=0.5, max_value=1_000.0,
              allow_nan=False, allow_infinity=False),
    min_size=8,
    max_size=64,
)
silences = st.floats(min_value=0.0, max_value=100_000.0,
                     allow_nan=False, allow_infinity=False)


def _fed(history: "list[float]") -> "tuple[PhiAccrual, float]":
    acc = PhiAccrual()
    now = 0.0
    acc.heartbeat(now)
    for gap in history:
        now += gap
        acc.heartbeat(now)
    return acc, now


@settings(max_examples=200, deadline=None)
@given(st.floats(min_value=-50.0, max_value=200.0,
                 allow_nan=False, allow_infinity=False),
       st.floats(min_value=0.0, max_value=50.0,
                 allow_nan=False, allow_infinity=False))
def test_phi_from_deviation_monotone_bounded(x, dx):
    lo, hi = phi_from_deviation(x), phi_from_deviation(x + dx)
    assert 0.0 <= lo <= hi <= DEFAULT_MAX_PHI


@settings(max_examples=100, deadline=None)
@given(intervals, silences, silences)
def test_phi_non_decreasing_under_silence(history, s1, s2):
    acc, now = _fed(history)
    a, b = sorted((s1, s2))
    assert acc.phi(now + a) <= acc.phi(now + b)


@settings(max_examples=100, deadline=None)
@given(intervals, st.floats(min_value=0.5, max_value=10_000.0,
                            allow_nan=False, allow_infinity=False))
def test_phi_resets_on_heartbeat(history, silence):
    acc, now = _fed(history)
    probe = now + silence
    acc.heartbeat(probe)
    assert acc.phi(probe) == 0.0
    # And the history stays sane: suspicion resumes from zero, bounded.
    assert 0.0 <= acc.phi(probe + silence) <= DEFAULT_MAX_PHI


@settings(max_examples=100, deadline=None)
@given(st.floats(min_value=2.0, max_value=100.0,
                 allow_nan=False, allow_infinity=False),
       st.floats(min_value=0.0, max_value=0.9,
                 allow_nan=False, allow_infinity=False),
       st.integers(min_value=8, max_value=32),
       silences)
def test_phi_monotone_in_spread_at_equal_mean(mean, spread, n, silence):
    """Alternating mean±d histories: same mean, wider d -> phi no higher
    at the same silence (the effective stddev floor keeps this true even
    as d -> 0)."""
    d = spread * mean
    tight, _ = _fed([mean] * (2 * n))
    wide, now = _fed([mean - d, mean + d] * n)
    assert wide.phi(now + silence) <= tight.phi(now + silence) + 1e-9
