"""Distributed causal tracing: context propagation, span stitching,
Perfetto export, and decision provenance (EXPLAIN).

Covers the full tentpole surface in-process:

- TraceContext wire/traceparent round-trips and child derivation;
- the gossip-envelope field (attach/extract on protobuf bytes) and its
  backward compatibility (decoders skip it, signatures unaffected);
- the bounded TraceStore, observed_span tagging, JSONL/Chrome export,
  and cross-peer stitching via merge_traces;
- engine integration: contexts bound at create/process, spans from two
  peers sharing one trace_id, explain_decision's quorum arithmetic;
- the O(1) TimelineStore index semantics.
"""

import json
import os

import pytest

from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import SessionNotFound
from hashgraph_tpu.obs.registry import MetricsRegistry
from hashgraph_tpu.obs.timeline import TimelineStore
from hashgraph_tpu.obs.trace import (
    TraceContext,
    TraceStore,
    attach_trace,
    current_context,
    extract_trace,
    load_spans_jsonl,
    merge_traces,
    trace_store,
    use_context,
)
from hashgraph_tpu.signing.stub import StubConsensusSigner
from hashgraph_tpu.types import CreateProposalRequest
from hashgraph_tpu.wire import Proposal, Vote

NOW = 1_700_000_000


def fresh_engine(ident: bytes, **kwargs) -> TpuConsensusEngine:
    kwargs.setdefault("capacity", 8)
    kwargs.setdefault("voter_capacity", 8)
    return TpuConsensusEngine(StubConsensusSigner(ident), **kwargs)


def make_request(expected: int = 2, owner: bytes = b"o" * 20):
    return CreateProposalRequest(
        name="p",
        payload=b"",
        proposal_owner=owner,
        expected_voters_count=expected,
        expiration_timestamp=600,
        liveness_criteria_yes=True,
    )


class TestTraceContext:
    def test_wire_roundtrip(self):
        ctx = TraceContext.generate()
        assert TraceContext.from_wire(ctx.to_wire()) == ctx
        assert len(ctx.to_wire()) == 25

    def test_wire_rejects_bad_length(self):
        with pytest.raises(ValueError):
            TraceContext.from_wire(b"short")

    def test_traceparent_roundtrip(self):
        ctx = TraceContext.generate()
        header = ctx.to_traceparent()
        assert header.startswith("00-")
        assert TraceContext.from_traceparent(header) == ctx

    def test_traceparent_rejects_junk(self):
        with pytest.raises(ValueError):
            TraceContext.from_traceparent("01-aa-bb-cc")

    def test_child_shares_trace_id(self):
        ctx = TraceContext.generate()
        kid = ctx.child()
        assert kid.trace_id == ctx.trace_id
        assert kid.span_id != ctx.span_id

    def test_use_context_none_is_noop(self):
        with use_context(None):
            assert current_context() is None

    def test_use_context_nests_and_restores(self):
        a, b = TraceContext.generate(), TraceContext.generate()
        with use_context(a):
            assert current_context() == a
            with use_context(b):
                assert current_context() == b
            assert current_context() == a
        assert current_context() is None


class TestEnvelopeField:
    def test_attach_then_decode_is_identical(self):
        vote = Vote(vote_id=7, vote_owner=b"abc", proposal_id=3, vote=True)
        ctx = TraceContext.generate()
        raw = attach_trace(vote.encode(), ctx)
        assert Vote.decode(raw) == vote  # unknown field skipped
        assert extract_trace(raw) == ctx

    def test_attach_on_proposal(self):
        proposal = Proposal(name="n", proposal_id=9, payload=b"pp")
        ctx = TraceContext.generate()
        raw = attach_trace(proposal.encode(), ctx)
        assert Proposal.decode(raw) == proposal
        assert extract_trace(raw) == ctx

    def test_extract_absent_is_none(self):
        assert extract_trace(Vote(vote_id=1).encode()) is None
        assert extract_trace(b"") is None

    def test_extract_never_raises_on_junk(self):
        for junk in (b"\xff" * 40, b"\x93\x0f", os.urandom(64)):
            extract_trace(junk)  # must not raise


class TestTraceStore:
    def test_bounded_rolling_window_with_drop_count(self):
        store = TraceStore(capacity=2, peer="t")
        ctx = TraceContext.generate()
        for i in range(5):
            store.record(f"s{i}", ctx.child(), 0.0, 0.1)
        # Rolling window: the NEWEST spans survive (a long-running server
        # can always capture an incident trace), evictions are counted.
        assert [s.name for s in store.spans()] == ["s3", "s4"]
        assert store.dropped == 3
        store.clear()
        assert store.spans() == [] and store.dropped == 0

    def test_disabled_records_nothing(self):
        store = TraceStore(peer="t")
        store.enabled = False
        store.record("s", TraceContext.generate(), 0.0, 0.1)
        assert store.spans() == []

    def test_peer_and_trace_filters(self):
        store = TraceStore(peer="default")
        a, b = TraceContext.generate(), TraceContext.generate()
        store.record("x", a, 0.0, 0.1, peer="p1")
        store.record("y", b, 0.0, 0.1, peer="p2")
        assert [s.name for s in store.spans(peer="p1")] == ["x"]
        assert [s.name for s in store.spans(trace_id=b.trace_id)] == ["y"]

    def test_jsonl_roundtrip(self, tmp_path):
        store = TraceStore(peer="t")
        ctx = TraceContext.generate()
        store.record(
            "s", ctx, 1.5, 0.25, parent=b"\x01" * 8, attrs={"k": 1}
        )
        path = str(tmp_path / "spans.jsonl")
        assert store.export_jsonl(path) == 1
        [span] = load_spans_jsonl(path)
        assert span.name == "s" and span.trace_id == ctx.trace_id
        assert span.parent_id == b"\x01" * 8 and span.attrs == {"k": 1}
        assert span.start == 1.5 and span.duration == 0.25

    def test_chrome_export_shape(self, tmp_path):
        store = TraceStore(peer="t")
        ctx = TraceContext.generate()
        store.record("s", ctx, 1.0, 0.5)
        store.instant("i", ctx, ts=2.0)
        path = str(tmp_path / "trace.json")
        store.export_chrome(path)
        with open(path) as fh:
            doc = json.load(fh)
        phases = {e["ph"] for e in doc["traceEvents"]}
        assert {"M", "X", "i"} <= phases
        x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
        assert x["ts"] == 1.0e6 and x["dur"] == 0.5e6
        assert x["args"]["trace_id"] == ctx.trace_id.hex()

    def test_merge_traces_stitches_and_orders(self, tmp_path):
        ctx = TraceContext.generate()
        a = TraceStore(peer="peer-a")
        b = TraceStore(peer="peer-b")
        a.record("create", ctx, 10.0, 0.5)
        b.record("process", ctx.child(), 11.0, 0.5, parent=ctx.span_id)
        b.instant("decided", ctx, ts=12.0)
        a_path, b_path = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        a.export_jsonl(a_path)
        b.export_jsonl(b_path)
        out = str(tmp_path / "merged.json")
        summary = merge_traces([a_path, b_path], out)
        assert summary["spans"] == 3
        assert summary["peers"] == ["peer-a", "peer-b"]
        assert summary["traces"] == {ctx.trace_id.hex(): 3}
        with open(out) as fh:
            doc = json.load(fh)
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] != "M"]
        assert names == ["create", "process", "decided"]  # causal order


class TestObservedSpanTagging:
    def test_tagged_only_under_context(self):
        from hashgraph_tpu.obs import observed_span
        from hashgraph_tpu.tracing import Tracer

        reg = MetricsRegistry()
        hist = reg.histogram("tagging_test_seconds")
        tracer = Tracer()
        before = len(trace_store.spans())
        with observed_span(tracer, "untagged.span", hist):
            pass
        assert len(trace_store.spans()) == before  # no ambient context
        ctx = TraceContext.generate()
        with use_context(ctx):
            with observed_span(tracer, "tagged.span", hist, votes=3):
                pass
        [span] = trace_store.spans(trace_id=ctx.trace_id)
        assert span.name == "tagged.span"
        assert span.parent_id == ctx.span_id
        assert span.attrs == {"votes": 3}
        assert hist.count == 2  # histogram observes either way


class TestEngineTracing:
    def two_peer_decided(self):
        a = fresh_engine(b"a" * 20)
        b = fresh_engine(b"b" * 20)
        proposal = a.create_proposal("s", make_request(), NOW)
        pid = proposal.proposal_id
        ctx = a.trace_context_of("s", pid)
        wire = attach_trace(proposal.encode(), ctx)
        with use_context(extract_trace(wire)):
            b.process_incoming_proposal("s", Proposal.decode(wire), NOW)
        va = a.cast_vote("s", pid, True, NOW + 1)
        vb = b.cast_vote("s", pid, True, NOW + 1)
        a.process_incoming_vote("s", vb.clone(), NOW + 2)
        b.process_incoming_vote("s", va.clone(), NOW + 2)
        return a, b, pid, ctx

    def test_cross_peer_spans_share_trace_id(self):
        a, b, pid, ctx = self.two_peer_decided()
        assert a.get_consensus_result("s", pid) is True
        b_ctx = b.trace_context_of("s", pid)
        assert b_ctx.trace_id == ctx.trace_id
        assert b_ctx.span_id != ctx.span_id
        spans = trace_store.spans(trace_id=ctx.trace_id)
        peers = {s.peer for s in spans}
        assert {"peer:" + (b"a" * 20).hex()[:12],
                "peer:" + (b"b" * 20).hex()[:12]} <= peers
        names = {s.name for s in spans}
        assert {"consensus.create_proposal", "consensus.process_proposal",
                "consensus.vote_applied", "consensus.decided"} <= names

    def test_create_without_ambient_roots_a_trace(self):
        engine = fresh_engine(os.urandom(20))
        proposal = engine.create_proposal("s", make_request(), NOW)
        ctx = engine.trace_context_of("s", proposal.proposal_id)
        assert ctx is not None and len(ctx.trace_id) == 16

    def test_create_under_ambient_joins_it(self):
        engine = fresh_engine(os.urandom(20))
        root = TraceContext.generate()
        with use_context(root):
            proposal = engine.create_proposal("s", make_request(), NOW)
        ctx = engine.trace_context_of("s", proposal.proposal_id)
        assert ctx.trace_id == root.trace_id

    def test_trace_context_of_unknown_is_none(self):
        engine = fresh_engine(os.urandom(20))
        assert engine.trace_context_of("s", 12345) is None


class TestExplainDecision:
    def test_explain_reached(self):
        a, b, pid, ctx = TestEngineTracing().two_peer_decided()
        verdict = a.explain_decision("s", pid)
        assert verdict["status"] == "reached" and verdict["result"] is True
        quorum = verdict["quorum"]
        assert quorum["expected_voters"] == 2
        assert quorum["rule"] == "unanimity (n <= 2)"
        assert quorum["required_votes"] == 2
        assert quorum["yes"] == 2 and quorum["no"] == 0
        assert quorum["reached"] and quorum["recomputed_result"] is True
        assert len(verdict["vote_chain"]) == 2
        owners = {c["owner"] for c in verdict["vote_chain"]}
        assert owners == {(b"a" * 20).hex(), (b"b" * 20).hex()}
        assert verdict["contributions"][(b"b" * 20).hex()]["via"] == "vote"
        assert verdict["timeline"]["outcome"] == "yes"
        assert verdict["trace"]["trace_id"] == ctx.trace_id.hex()
        json.dumps(verdict)  # JSON-safe end to end

    def test_explain_quorum_arithmetic_ceil_2n3(self):
        engine = fresh_engine(os.urandom(20))
        proposal = engine.create_proposal("s", make_request(expected=7), NOW)
        verdict = engine.explain_decision("s", proposal.proposal_id)
        quorum = verdict["quorum"]
        assert quorum["rule"] == "div_ceil(2n, 3)"
        assert quorum["required_votes"] == (2 * 7 + 2) // 3 == 5
        assert verdict["status"] == "active" and verdict["result"] is None
        assert quorum["recomputed_result"] is None

    def test_explain_timeout_failure(self):
        from hashgraph_tpu.errors import InsufficientVotesAtTimeout

        engine = fresh_engine(os.urandom(20))
        # n=2 unanimity with zero votes: undecidable at timeout.
        proposal = engine.create_proposal("s", make_request(expected=2), NOW)
        with pytest.raises(InsufficientVotesAtTimeout):
            engine.handle_consensus_timeout("s", proposal.proposal_id, NOW + 700)
        verdict = engine.explain_decision("s", proposal.proposal_id)
        assert verdict["status"] == "failed" and verdict["by_timeout"] is True
        assert verdict["quorum"]["total"] == 0

    def test_explain_unknown_raises(self):
        engine = fresh_engine(os.urandom(20))
        with pytest.raises(SessionNotFound):
            engine.explain_decision("s", 424242)

    def test_durable_engine_overlays_wal_watermark(self, tmp_path):
        from hashgraph_tpu.wal import DurableEngine

        durable = DurableEngine(
            fresh_engine(os.urandom(20)), str(tmp_path / "wal")
        )
        with durable:
            proposal = durable.create_proposal("s", make_request(), NOW)
            verdict = durable.explain_decision("s", proposal.proposal_id)
            assert verdict["wal"]["last_lsn"] >= 1
            assert verdict["wal"]["checkpoint_watermark"] == 0
            assert verdict["wal"]["fsync_policy"] in ("always", "batch", "off")


class TestTimelineIndex:
    def make(self):
        reg = MetricsRegistry()
        return TimelineStore(reg.histogram("idx_test_seconds"), completed_capacity=3)

    def test_find_after_forget_is_o1_indexed(self):
        store = self.make()
        store.created(0, "s", 11, NOW, 1.0)
        store.decided(0, "yes", NOW + 1, 2.0)
        store.forget(0)
        tl = store.find("s", 11)
        assert tl is not None and tl.outcome == "yes"
        assert store.find("s", 99) is None

    def test_eviction_drops_index_entries(self):
        store = self.make()
        for i in range(5):
            store.created(i, "s", 100 + i, NOW, 1.0)
            store.forget(i)
        # capacity 3: the two oldest aged out of ring AND index.
        assert store.find("s", 100) is None
        assert store.find("s", 101) is None
        for pid in (102, 103, 104):
            assert store.find("s", pid) is not None

    def test_pid_reuse_finds_most_recent(self):
        store = self.make()
        store.created(0, "s", 7, NOW, 1.0)
        store.decided(0, "no", NOW + 1, 2.0)
        store.forget(0)
        store.created(1, "s", 7, NOW + 2, 3.0)
        store.decided(1, "yes", NOW + 3, 4.0)
        store.forget(1)
        assert store.find("s", 7).outcome == "yes"
