"""Deterministic chaos harness (hashgraph_tpu.sim).

Covers the three layers separately, then the whole corpus:

- core/transport units — seeded scheduler ordering, per-link fault
  injection (partition, asymmetric loss, drop, dup, mutation), sim
  futures pumping virtual time, shed backpressure;
- the engine hardenings the harness forced — dangling-vote rejection
  and the double-sign fork-conviction bar (the defamation regression);
- scenario acceptance — every corpus scenario passes all three verdicts
  at a pinned seed, the SAME seed twice yields byte-identical verdict
  JSON, and a deliberately blinded run (evidence layer disabled) FAILS
  the accountability verdict — the harness can detect its own blindness.
"""

import json

import pytest

from hashgraph_tpu import StubConsensusSigner, build_vote
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.bridge.client import BridgeConnectionLost
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.obs.health import GRADE_FAULTY, GRADE_SUSPECT
from hashgraph_tpu.sim import (
    SCENARIOS,
    ByzantineActor,
    SimCluster,
    SimNetwork,
    SimScheduler,
    SimTransport,
    run_scenario,
    verify_evidence_record,
)
from hashgraph_tpu.sim.scenarios import _blind, _finish

from common import NOW


SEED = 424242


# ── core ───────────────────────────────────────────────────────────────


class TestScheduler:
    def test_events_fire_in_time_then_insertion_order(self):
        sched = SimScheduler(1)
        order = []
        sched.at(5, lambda: order.append("late"))
        sched.at(1, lambda: order.append("a"))
        sched.at(1, lambda: order.append("b"))
        sched.at(0, lambda: order.append("now"))
        sched.run_until_idle()
        assert order == ["now", "a", "b", "late"]
        assert sched.now == 5

    def test_advance_requires_idle_queue(self):
        sched = SimScheduler(1)
        sched.at(1, lambda: None)
        with pytest.raises(RuntimeError):
            sched.advance(10)
        sched.run_until_idle()
        sched.advance(10)
        assert sched.now == 11


def _echo_endpoint(log):
    def dispatch(opcode, payload):
        log.append((opcode, payload))
        return P.STATUS_OK, P.u32(len(payload))

    return dispatch


class TestSimTransportFaults:
    def _fabric(self, seed=7):
        sched = SimScheduler(seed)
        net = SimNetwork(sched)
        log = []
        net.register("srv", _echo_endpoint(log))
        transport = SimTransport(net, "cli")
        transport.connect("srv", "srv", 0)
        return sched, net, transport, log

    def test_request_round_trip(self):
        _, _, transport, log = self._fabric()
        future = transport.request("srv", P.OP_PING, b"xy")
        assert future.result(1).u32() == 2  # result() pumps virtual time
        assert log == [(P.OP_PING, b"xy")]

    def test_partition_fails_typed_without_dispatch(self):
        _, net, transport, log = self._fabric()
        net.partition(["cli"], ["srv"])
        future = transport.request("srv", P.OP_PING, b"")
        with pytest.raises(BridgeConnectionLost):
            future.result(1)
        assert log == []
        net.heal_partition()
        assert transport.request("srv", P.OP_PING, b"").result(1) == 0 or True

    def test_asymmetric_partition_executes_but_loses_response(self):
        _, net, transport, log = self._fabric()
        # Response path srv->cli blocked: the request EXECUTES, the
        # caller still sees a typed loss.
        net.partition(["srv"], ["cli"], bidirectional=False)
        future = transport.request("srv", P.OP_PING, b"pay")
        with pytest.raises(BridgeConnectionLost):
            future.result(1)
        assert log == [(P.OP_PING, b"pay")]

    def test_drop_is_seed_deterministic(self):
        outcomes = []
        for _ in range(2):
            _, net, transport, log = self._fabric(seed=99)
            net.set_link("cli", "srv", drop_p=0.5)
            got = []
            for i in range(20):
                future = transport.request("srv", P.OP_PING, bytes([i]))
                try:
                    future.result(1)
                    got.append(True)
                except BridgeConnectionLost:
                    got.append(False)
            outcomes.append(got)
        assert outcomes[0] == outcomes[1]
        assert True in outcomes[0] and False in outcomes[0]

    def test_duplicate_dispatches_twice_resolves_once(self):
        _, net, transport, log = self._fabric()
        net.set_link("cli", "srv", dup_p=1.0)
        future = transport.request("srv", P.OP_PING, b"z")
        assert future.result(1).u32() == 1
        transport._network.scheduler.run_until_idle()
        assert len(log) == 2  # the frame hit the endpoint twice

    def test_mutation_hook_rewrites_request_bytes(self):
        _, net, transport, log = self._fabric()
        net.set_link(
            "cli", "srv",
            mutate=lambda opcode, payload: payload + b"!!",
        )
        future = transport.request("srv", P.OP_PING, b"ab")
        assert future.result(1).u32() == 4
        assert log == [(P.OP_PING, b"ab!!")]
        assert net.stats.mutated == 1

    def test_queue_cap_sheds(self):
        sched = SimScheduler(3)
        net = SimNetwork(sched)
        net.register("srv", _echo_endpoint([]))
        transport = SimTransport(net, "cli", max_queue_bytes=128)
        transport.connect("srv", "srv", 0)
        big = bytes(60)
        assert transport.try_request("srv", P.OP_PING, big) is not None
        assert transport.try_request("srv", P.OP_PING, big) is None  # shed
        assert transport.channel("srv").shed_total == 1

    def test_down_endpoint_fails_typed(self):
        _, net, transport, _ = self._fabric()
        net.mark_down("srv")
        future = transport.request("srv", P.OP_PING, b"")
        with pytest.raises(BridgeConnectionLost):
            future.result(1)


# ── engine hardenings the harness forced ───────────────────────────────


def _session_with_chain(n_votes=2):
    from hashgraph_tpu import CreateProposalRequest
    from hashgraph_tpu.engine import TpuConsensusEngine

    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x42" * 20), capacity=8, voter_capacity=8
    )
    proposal = engine.create_proposal(
        "s",
        CreateProposalRequest(
            name="p", payload=b"", proposal_owner=b"o",
            expected_voters_count=8, expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        ),
        NOW,
    )
    chain = proposal.clone()
    for i in range(n_votes):
        signer = StubConsensusSigner(bytes([i + 1]) * 20)
        chain.votes.append(build_vote(chain, True, signer, NOW + i))
    return engine, proposal.proposal_id, chain


class TestDanglingVoteGuard:
    def test_gap_vote_rejected_then_repaired_by_delivery(self):
        """A first-time voter's vote whose received_hash skips over a
        vote this engine never saw is rejected typed (it would make the
        chain unrepairable); the full-chain delivery then repairs."""
        engine, pid, chain = _session_with_chain(3)
        receiver_engine, _, _ = _session_with_chain(0)
        receiver = receiver_engine
        base = chain.clone()
        base.votes = []
        receiver.process_incoming_proposal("s", base, NOW)
        assert int(
            receiver.ingest_votes([("s", chain.votes[0].clone())], NOW)[0]
        ) == int(StatusCode.OK)
        # votes[1] dropped; votes[2] dangles and must NOT be accepted.
        assert int(
            receiver.ingest_votes([("s", chain.votes[2].clone())], NOW)[0]
        ) == int(StatusCode.RECEIVED_HASH_MISMATCH)
        assert len(receiver.get_proposal("s", pid).votes) == 1
        # Anti-entropy style full-chain delivery extends cleanly.
        assert receiver.deliver_proposal("s", chain.clone(), NOW + 1) == int(
            StatusCode.OK
        )
        assert len(receiver.get_proposal("s", pid).votes) == 3

    def test_first_vote_claiming_a_link_onto_empty_chain_rejected(self):
        engine, pid, chain = _session_with_chain(2)
        receiver_engine, _, _ = _session_with_chain(0)
        base = chain.clone()
        base.votes = []
        receiver_engine.process_incoming_proposal("s", base, NOW)
        # votes[1] links votes[0]; an empty chain has no such tail.
        assert int(
            receiver_engine.ingest_votes([("s", chain.votes[1].clone())], NOW)[0]
        ) == int(StatusCode.RECEIVED_HASH_MISMATCH)

    def test_same_batch_chained_run_still_applies(self):
        engine, pid, chain = _session_with_chain(3)
        receiver_engine, _, _ = _session_with_chain(0)
        base = chain.clone()
        base.votes = []
        receiver_engine.process_incoming_proposal("s", base, NOW)
        statuses = receiver_engine.ingest_votes(
            [("s", v.clone()) for v in chain.votes], NOW
        )
        assert [int(s) for s in statuses] == [int(StatusCode.OK)] * 3

    def test_redelivered_duplicate_keeps_duplicate_status(self):
        engine, pid, chain = _session_with_chain(2)
        receiver_engine, _, _ = _session_with_chain(0)
        base = chain.clone()
        base.votes = []
        receiver_engine.process_incoming_proposal("s", base, NOW)
        receiver_engine.ingest_votes(
            [("s", v.clone()) for v in chain.votes], NOW
        )
        # The first vote redelivered: its received_hash no longer matches
        # the tail, but a KNOWN owner must keep the duplicate-shaped
        # status (the equivocation probe depends on it).
        assert int(
            receiver_engine.ingest_votes([("s", chain.votes[0].clone())], NOW)[0]
        ) == int(StatusCode.DUPLICATE_VOTE)


# ── scenarios: the acceptance criteria ─────────────────────────────────


class TestScenarioCorpus:
    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_scenario_passes_all_three_verdicts(self, name, tmp_path):
        result = run_scenario(name, SEED, root=str(tmp_path))
        assert result["verdicts"]["convergence"]["ok"], result["verdicts"]
        assert result["verdicts"]["accountability"]["ok"], result["verdicts"]
        assert result["verdicts"]["safety"]["ok"], result["verdicts"]
        assert result["passed"], result["checks"]

    def test_same_seed_yields_byte_identical_verdict_json(self):
        first = json.dumps(run_scenario("storm", SEED), sort_keys=True)
        second = json.dumps(run_scenario("storm", SEED), sort_keys=True)
        assert first == second

    def test_different_seeds_change_the_schedule_not_the_verdict(self):
        a = run_scenario("partition-heal", 1)
        b = run_scenario("partition-heal", 2)
        assert a["passed"] and b["passed"]
        assert (
            a["verdicts"]["convergence"]["fingerprints"]
            != b["verdicts"]["convergence"]["fingerprints"]
        )

    def test_blind_run_fails_accountability(self):
        """Acceptance: a deliberately broken injector-run (evidence layer
        disabled) FAILS the accountability verdict — the harness detects
        its own blindness instead of vacuously passing."""
        result = run_scenario("equivocator", SEED, blind=True)
        assert not result["passed"]
        accountability = result["verdicts"]["accountability"]
        assert not accountability["ok"]
        assert accountability["missed_culprits"]  # culprit went unconvicted


class TestAccountabilityDetail:
    def test_equivocator_evidence_verifies_offline(self, tmp_path):
        spec = SCENARIOS["equivocator"]
        with SimCluster(str(tmp_path), SEED, **spec.cluster_kwargs) as cluster:
            culprits, _checks, _detail = spec.body(cluster)
            [culprit] = culprits
            assert culprits[culprit] == GRADE_FAULTY
            for peer in cluster.live_peers():
                convicted = peer.monitor.convicted_peers(now=cluster.now)
                assert set(convicted) == {culprit}
                assert convicted[culprit]["grade"] == GRADE_FAULTY
                assert convicted[culprit]["evidence"] >= 1
                for record in peer.monitor.evidence():
                    ok, reason = verify_evidence_record(
                        record, StubConsensusSigner
                    )
                    assert ok, reason
                # The conviction also rides the snapshot surface the
                # bridge serves (health_report "convicted" block).
                report = peer.engine.health_report(cluster.now)
                assert set(report["convicted"]) == {culprit}
            result = _finish(cluster, culprits, _checks, _detail)
            assert result["passed"]

    def test_forker_convicted_only_with_double_sign_evidence(self, tmp_path):
        spec = SCENARIOS["forker"]
        with SimCluster(str(tmp_path), SEED, **spec.cluster_kwargs) as cluster:
            culprits, _checks, _detail = spec.body(cluster)
            [culprit] = culprits
            assert culprits[culprit] == GRADE_SUSPECT
            for peer in cluster.live_peers():
                for record in peer.monitor.evidence():
                    assert record["offender"] == culprit
                    ok, reason = verify_evidence_record(
                        record, StubConsensusSigner
                    )
                    assert ok, reason

    def test_byzantine_actor_signs_genuinely(self, tmp_path):
        with SimCluster(str(tmp_path), SEED) as cluster:
            byz = ByzantineActor(cluster)
            session = cluster.create_session(cluster.peer(0), "x")
            a_bytes, b_bytes = byz.equivocate(session)
            from hashgraph_tpu.wire import Vote

            for raw in (a_bytes, b_bytes):
                vote = Vote.decode(raw)
                assert vote.vote_owner == byz.identity
                assert StubConsensusSigner.verify(
                    vote.vote_owner, vote.signing_payload(), vote.signature
                )

    def test_blind_helper_actually_pauses_health(self, tmp_path):
        with SimCluster(str(tmp_path), SEED) as cluster:
            _blind(cluster)
            byz = ByzantineActor(cluster)
            session = cluster.create_session(cluster.peer(0), "x")
            byz.equivocate(session)
            for peer in cluster.live_peers():
                assert peer.monitor.evidence_count() == 0


class TestCrashRestartPlumbing:
    def test_restart_recovers_identity_and_state(self, tmp_path):
        with SimCluster(str(tmp_path), SEED) as cluster:
            session = cluster.create_session(cluster.peer(0), "keep")
            cluster.vote_all(session)
            victim = cluster.peer(1)
            identity = victim.identity
            before = cluster.fingerprints()[victim.name]
            victim.crash()
            assert not cluster.network.is_up(victim.name)
            victim.restart()
            assert victim.identity == identity  # same key, same identity
            assert victim.last_recovery.records_applied > 0
            assert cluster.fingerprints()[victim.name] == before

    def test_wiped_restart_is_fresh(self, tmp_path):
        with SimCluster(str(tmp_path), SEED) as cluster:
            session = cluster.create_session(cluster.peer(0), "gone")
            cluster.vote_all(session)
            victim = cluster.peer(1)
            victim.crash()
            victim.restart(wipe=True)
            assert victim.last_recovery.records_applied == 0
            assert victim.engine.occupancy()["live_sessions"] == 0
