"""Proposal-id birthday-collision survival (round-3 VERDICT item 1).

u32 proposal ids collide with probability ~n²/2³³ per scope; at the
north-star population (100k concurrent proposals) a collision is
near-certain. The reference's HashMap insert silently overwrites the
incumbent session (reference: src/storage.rs:225-230); round-2's engine
crashed on scope deletion instead. The fix under test: locally-generated
ids are regenerated while taken, so collisions are unobservable; incoming
network proposals (whose ids are signed into vote chains) still raise
ProposalAlreadyExist.
"""

from __future__ import annotations

import itertools

import pytest

import hashgraph_tpu.protocol as protocol_mod
import hashgraph_tpu.types as types_mod
from hashgraph_tpu import (
    CreateProposalRequest,
    ProposalAlreadyExist,
    StubConsensusSigner,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from tests.common import NOW, make_service


def request(n=3, name="p"):
    return CreateProposalRequest(
        name=name,
        payload=b"x",
        proposal_owner=b"owner",
        expected_voters_count=n,
        expiration_timestamp=3600,
        liveness_criteria_yes=True,
    )


@pytest.fixture
def collide(monkeypatch):
    """Force every into_proposal to mint the SAME id (42) while the shared
    regeneration path (protocol.regenerate_until_unique) draws from a
    deterministic counter — the seeded-generate_id harness the verdict
    prescribes. types.py binds its own reference to generate_id at import
    time, so the two patches are independent by construction."""
    monkeypatch.setattr(types_mod, "generate_id", lambda: 42)
    counter = itertools.count(100)
    monkeypatch.setattr(protocol_mod, "generate_id", lambda: next(counter))
    return counter


def make_engine(**kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("voter_capacity", 8)
    kw.setdefault("max_sessions_per_scope", 1000)
    return TpuConsensusEngine(StubConsensusSigner(b"self-peer-identity-1"), **kw)


def test_engine_create_proposal_regenerates_on_collision(collide):
    engine = make_engine()
    p1 = engine.create_proposal("s", request(), NOW)
    p2 = engine.create_proposal("s", request(), NOW)
    p3 = engine.create_proposal("s", request(), NOW)
    assert p1.proposal_id == 42
    assert sorted([p2.proposal_id, p3.proposal_id]) == [100, 101]
    # All three are independently addressable and intact.
    for p in (p1, p2, p3):
        got = engine.get_proposal("s", p.proposal_id)
        assert got.proposal_id == p.proposal_id
    # Scope deletion — the round-2 crash site — walks every index entry.
    engine.delete_scope("s")
    assert engine.get_scope_stats("s").total_sessions == 0


def test_engine_same_id_in_different_scopes_is_not_a_collision(collide):
    engine = make_engine()
    pa = engine.create_proposal("a", request(), NOW)
    pb = engine.create_proposal("b", request(), NOW)
    assert pa.proposal_id == 42 and pb.proposal_id == 42


def test_engine_create_proposals_batch_regenerates_within_batch(collide, monkeypatch):
    """The batch path draws ids in one urandom read with vectorized
    rejection (against live pids AND intra-batch duplicates); force the
    first draw to collide wholesale and check every id is re-drawn."""
    import os

    draws = [b"\x2a\x00\x00\x00" * 5]  # every id = 42, all colliding
    counter = itertools.count(200)

    def fake_urandom(n):
        if draws:
            return draws.pop(0)
        return b"".join(
            int(next(counter)).to_bytes(4, "little") for _ in range(n // 4)
        )

    monkeypatch.setattr(os, "urandom", fake_urandom)
    engine = make_engine()
    engine.create_proposal("s", request(), NOW)  # scalar path takes id 42
    batch = engine.create_proposals("s", [request() for _ in range(5)], NOW)
    pids = [p.proposal_id for p in batch]
    assert len(set(pids)) == 5, pids
    assert 42 not in pids
    assert set(pids) == {200, 201, 202, 203, 204}, pids
    # And against pre-existing sessions, not just batch-internal.
    batch2 = engine.create_proposals("s", [request() for _ in range(2)], NOW)
    pids2 = [p.proposal_id for p in batch2]
    assert len(set(pids + pids2 + [42])) == 8
    engine.delete_scope("s")


def test_engine_incoming_duplicate_still_raises(collide):
    engine = make_engine()
    engine.create_proposal("s", request(), NOW)  # takes id 42
    incoming = request().into_proposal(NOW)  # also id 42; network-born
    with pytest.raises(ProposalAlreadyExist):
        engine.process_incoming_proposal("s", incoming, NOW)
    statuses = engine.ingest_proposals([("s", request().into_proposal(NOW))], NOW)
    from hashgraph_tpu import StatusCode

    assert statuses[0] == int(StatusCode.PROPOSAL_ALREADY_EXIST)


def test_service_create_proposal_regenerates_on_collision(collide):
    service = make_service(max_sessions=100)
    p1 = service.create_proposal("s", request(), NOW)
    p2 = service.create_proposal("s", request(), NOW)
    assert p1.proposal_id == 42
    assert p2.proposal_id != 42
    # Both sessions are live — the incumbent was NOT silently replaced.
    assert service.storage().get_session("s", p1.proposal_id) is not None
    assert service.storage().get_session("s", p2.proposal_id) is not None


def test_engine_100k_create_delete_smoke():
    """North-star-scale population: 100k proposals in one scope under real
    (random) id generation — expected ~1.2 birthday collisions per run —
    must create, be fully addressable, and delete without a KeyError,
    deterministically. Pool capacity is far smaller, so most sessions take
    the host-spill path; both substrates share the same index discipline."""
    engine = make_engine(
        capacity=1024, voter_capacity=4, max_sessions_per_scope=200_000
    )
    total = 0
    for _ in range(10):
        batch = engine.create_proposals(
            "big", [request(n=3) for _ in range(10_000)], NOW
        )
        total += len(batch)
    assert total == 100_000
    stats = engine.get_scope_stats("big")
    assert stats.total_sessions == 100_000
    engine.delete_scope("big")  # round-2 crash site: double-del KeyError
    assert engine.get_scope_stats("big").total_sessions == 0
