"""Checkpoint/resume: engine <-> ConsensusStorage round-trips.

Device tensors are a cache; a restored engine must be observably identical —
same results, same continued behavior for in-flight sessions, same stats.
"""

import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    InMemoryConsensusStorage,
    NetworkType,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine

from common import NOW, random_stub_signer


def request(n=3, name="p", exp=1000, liveness=True):
    return CreateProposalRequest(
        name=name,
        payload=b"x",
        proposal_owner=b"o",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


class TestCheckpointResume:
    def test_roundtrip_preserves_everything(self):
        signer = random_stub_signer()
        engine = TpuConsensusEngine(signer, capacity=16, voter_capacity=8)
        engine.scope("beta").with_network_type(NetworkType.P2P).initialize()

        # Session 1: decided YES.
        pid1 = engine.create_proposal("alpha", request(3, "a"), NOW).proposal_id
        engine.cast_vote("alpha", pid1, True, NOW)
        v = build_vote(engine.get_proposal("alpha", pid1), True, random_stub_signer(), NOW)
        engine.process_incoming_vote("alpha", v, NOW)
        # Session 2: in flight with one vote.
        pid2 = engine.create_proposal("beta", request(5, "b"), NOW + 1).proposal_id
        engine.cast_vote("beta", pid2, False, NOW + 1)
        # Session 3: zero votes, active.
        pid3 = engine.create_proposal("alpha", request(4, "c"), NOW + 2).proposal_id

        storage = InMemoryConsensusStorage()
        assert engine.save_to_storage(storage) == 3

        restored = TpuConsensusEngine(signer, capacity=16, voter_capacity=8)
        assert restored.load_from_storage(storage) == 3

        assert restored.get_consensus_result("alpha", pid1) is True
        assert restored.get_consensus_result("beta", pid2) is None
        assert restored.get_scope_config("beta").network_type == NetworkType.P2P

        # The in-flight session continues correctly: two more NO votes on a
        # 5-voter P2P session -> 3 NO >= ceil(5*2/3)=4? No: req=4, so still
        # undecided; timeout decides NO (liveness=True fills YES silent...).
        for _ in range(2):
            vote = build_vote(
                restored.get_proposal("beta", pid2), False, random_stub_signer(), NOW + 2
            )
            restored.process_incoming_vote("beta", vote, NOW + 2)
        session = restored.export_session("beta", pid2)
        assert len(session.votes) == 3
        # Round tracking continued from the restored round.
        assert session.proposal.round == 4  # P2P: 1 + 3 votes

        # Same-voter duplicate is still rejected after restore.
        from hashgraph_tpu import UserAlreadyVoted

        with pytest.raises(UserAlreadyVoted):
            restored.cast_vote("beta", pid2, True, NOW + 3)

        stats = restored.get_scope_stats("alpha")
        assert stats.total_sessions == 2
        assert stats.consensus_reached == 1
        assert stats.active_sessions == 1

    def test_restore_failed_session_without_votes(self):
        signer = random_stub_signer()
        engine = TpuConsensusEngine(signer, capacity=8, voter_capacity=8)
        pid = engine.create_proposal(
            "s", request(4, liveness=False, exp=50), NOW
        ).proposal_id
        # Timeout with zero votes and liveness=False -> 4 silent as NO -> NO.
        assert engine.handle_consensus_timeout("s", pid, NOW + 60) is False

        storage = InMemoryConsensusStorage()
        engine.save_to_storage(storage)
        restored = TpuConsensusEngine(signer, capacity=8, voter_capacity=8)
        restored.load_from_storage(storage)
        assert restored.get_consensus_result("s", pid) is False

    def test_idempotent_load(self):
        signer = random_stub_signer()
        engine = TpuConsensusEngine(signer, capacity=8, voter_capacity=8)
        engine.create_proposal("s", request(3), NOW)
        storage = InMemoryConsensusStorage()
        engine.save_to_storage(storage)
        restored = TpuConsensusEngine(signer, capacity=8, voter_capacity=8)
        assert restored.load_from_storage(storage) == 1
        assert restored.load_from_storage(storage) == 0  # no duplicates
        assert restored.get_scope_stats("s").total_sessions == 1
