"""Parity: vectorized chain kernel vs the scalar validate_vote_chain oracle.

Covers valid generated chains, every adversarial mutation class from
tests/vote_validation_tests.rs, and the hash-index shadowing edge (duplicate
vote_hash values, where the reference's last-occurrence-wins map is
load-bearing).
"""

import numpy as np
import pytest

from hashgraph_tpu import CreateProposalRequest, build_vote
from hashgraph_tpu.errors import (
    ConsensusError,
    ParentHashMismatch,
    ReceivedHashMismatch,
    StatusCode,
)
from hashgraph_tpu.ops.chain import (
    chain_kernel,
    chain_kernel_batch,
    first_chain_error,
    pack_chain,
)
from hashgraph_tpu.protocol import validate_vote_chain
from hashgraph_tpu.wire import Vote

from common import NOW, random_stub_signer


def scalar_code(votes) -> int:
    try:
        validate_vote_chain(votes)
        return int(StatusCode.OK)
    except ConsensusError as exc:
        return int(exc.code)


def device_code(votes, pad_to=None) -> int:
    packed = pack_chain(votes, pad_to=pad_to)
    statuses = chain_kernel(
        packed["vote_hash"],
        packed["received_hash"],
        packed["parent_hash"],
        packed["owner"],
        packed["ts"],
        packed["valid"],
    )
    return first_chain_error(statuses)


def build_chain(n_votes=6, n_signers=3, seed=0, now=NOW):
    """A structurally valid chain via the real build_vote linking rules."""
    rng = np.random.default_rng(seed)
    signers = [random_stub_signer() for _ in range(n_signers)]
    proposal = CreateProposalRequest(
        "chain", b"", b"o", 64, 1000, True
    ).into_proposal(now)
    for i in range(n_votes):
        signer = signers[int(rng.integers(n_signers))]
        vote = build_vote(proposal, bool(rng.random() < 0.5), signer, now + i)
        proposal.votes.append(vote)
    return proposal.votes


class TestChainParity:
    def _check(self, votes, pad_to=None):
        assert device_code(votes, pad_to) == scalar_code(votes)

    @pytest.mark.parametrize("seed", range(5))
    def test_valid_chains(self, seed):
        votes = build_chain(n_votes=8, n_signers=3, seed=seed)
        assert scalar_code(votes) == int(StatusCode.OK)
        self._check(votes)

    def test_padding_is_inert(self):
        votes = build_chain(n_votes=5)
        self._check(votes, pad_to=16)

    def test_tampered_received_hash(self):
        votes = build_chain(n_votes=5)
        votes[3].received_hash = b"\x13" * 32
        assert scalar_code(votes) == int(StatusCode.RECEIVED_HASH_MISMATCH)
        self._check(votes)

    def test_reordered_votes(self):
        votes = build_chain(n_votes=6)
        votes[2], votes[4] = votes[4], votes[2]
        self._check(votes)

    def test_received_ts_regression(self):
        votes = build_chain(n_votes=4)
        # Make the previous vote's timestamp exceed this one's while keeping
        # the hash link intact: bump vote 2's ts and re-link vote 3 to it.
        votes[2].timestamp = votes[3].timestamp + 100
        from hashgraph_tpu.protocol import compute_vote_hash

        votes[2].vote_hash = compute_vote_hash(votes[2])
        votes[3].received_hash = votes[2].vote_hash
        assert scalar_code(votes) == int(StatusCode.RECEIVED_HASH_MISMATCH)
        self._check(votes)

    def test_parent_wrong_owner(self):
        votes = build_chain(n_votes=6, n_signers=2, seed=3)
        # Find a vote with a parent link and point it at another owner's vote.
        linked = [i for i, v in enumerate(votes) if v.parent_hash]
        if not linked:
            pytest.skip("chain produced no parent links")
        i = linked[0]
        other = next(
            j for j, v in enumerate(votes) if v.vote_owner != votes[i].vote_owner
        )
        votes[i].parent_hash = votes[other].vote_hash
        assert scalar_code(votes) == int(StatusCode.PARENT_HASH_MISMATCH)
        self._check(votes)

    def test_parent_points_forward(self):
        votes = build_chain(n_votes=6, n_signers=2, seed=1)
        # Same-owner pair (i earlier, j later): make i's parent point at j.
        by_owner: dict[bytes, list[int]] = {}
        for idx, v in enumerate(votes):
            by_owner.setdefault(v.vote_owner, []).append(idx)
        pair = next(idxs for idxs in by_owner.values() if len(idxs) >= 2)
        earlier, later = pair[0], pair[1]
        votes[earlier].parent_hash = votes[later].vote_hash
        assert scalar_code(votes) == int(StatusCode.PARENT_HASH_MISMATCH)
        self._check(votes)

    def test_unknown_parent_hash(self):
        votes = build_chain(n_votes=4)
        votes[2].parent_hash = b"\x77" * 32
        assert scalar_code(votes) == int(StatusCode.PARENT_HASH_MISMATCH)
        self._check(votes)

    def test_shadowed_hash_last_occurrence_wins(self):
        """Two votes share a vote_hash; the hash index must resolve to the
        LAST one. If the last occurrence is by a different owner, a parent
        link to the (valid) earlier vote still fails — exact reference
        behavior (utils.rs:181-184 insert order)."""
        votes = build_chain(n_votes=5, n_signers=2, seed=2)
        by_owner: dict[bytes, list[int]] = {}
        for idx, v in enumerate(votes):
            by_owner.setdefault(v.vote_owner, []).append(idx)
        pair = next(idxs for idxs in by_owner.values() if len(idxs) >= 2)
        earlier, later = pair[0], pair[1]
        # later vote's parent -> earlier vote's hash (this is the normal
        # build_vote linking; force it in case the chain chose otherwise).
        votes[later].parent_hash = votes[earlier].vote_hash
        assert scalar_code(votes) == int(StatusCode.OK)
        self._check(votes)
        # Now shadow: a different owner's final vote claims the same hash.
        other = next(
            i for i, v in enumerate(votes) if v.vote_owner != votes[earlier].vote_owner
        )
        shadow = votes[other].clone()
        shadow.vote_hash = votes[earlier].vote_hash
        shadow.received_hash = b""
        shadow.parent_hash = b""
        shadow.timestamp = votes[-1].timestamp
        votes.append(shadow)
        assert scalar_code(votes) == int(StatusCode.PARENT_HASH_MISMATCH)
        self._check(votes)

    def test_long_hash_canonicalisation(self):
        votes = build_chain(n_votes=3)
        votes[1].parent_hash = b"\x55" * 64  # over 32 bytes, unknown
        assert scalar_code(votes) == int(StatusCode.PARENT_HASH_MISMATCH)
        self._check(votes)

    @pytest.mark.parametrize("seed", range(4))
    def test_randomized_mutations(self, seed):
        rng = np.random.default_rng(100 + seed)
        votes = build_chain(n_votes=10, n_signers=4, seed=seed)
        for _ in range(3):
            i = int(rng.integers(1, len(votes)))
            kind = rng.random()
            if kind < 0.3:
                votes[i].received_hash = bytes(rng.integers(0, 256, 32, np.uint8))
            elif kind < 0.6:
                votes[i].parent_hash = bytes(rng.integers(0, 256, 32, np.uint8))
            elif kind < 0.8:
                votes[i].timestamp = int(rng.integers(0, NOW * 2))
            else:
                j = int(rng.integers(0, len(votes)))
                votes[i], votes[j] = votes[j], votes[i]
        self._check(votes)

    def test_batched_kernel(self):
        """vmap over a proposal batch matches per-proposal results."""
        chains = [build_chain(n_votes=6, seed=s) for s in range(4)]
        chains[1][2].received_hash = b"\x99" * 32
        chains[3][4].parent_hash = b"\x42" * 32
        pad = max(len(c) for c in chains)
        packs = [pack_chain(c, pad_to=pad) for c in chains]
        batch = {
            k: np.stack([p[k] for p in packs]) for k in packs[0]
        }
        statuses = chain_kernel_batch(
            batch["vote_hash"],
            batch["received_hash"],
            batch["parent_hash"],
            batch["owner"],
            batch["ts"],
            batch["valid"],
        )
        for i, chain in enumerate(chains):
            assert first_chain_error(np.asarray(statuses)[i]) == scalar_code(chain)
