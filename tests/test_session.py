"""Session engine tests (reference: src/session.rs:407-700 inline tests)."""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    build_vote,
)
from hashgraph_tpu.errors import (
    DuplicateVote,
    InvalidConsensusThreshold,
    InvalidTimeout,
    MaxRoundsExceeded,
    SessionNotActive,
)
from hashgraph_tpu.session import ConsensusSession, ConsensusState
from hashgraph_tpu.signing import StubConsensusSigner

from common import NOW

U32_MAX = 0xFFFFFFFF


def signer(tag: bytes) -> StubConsensusSigner:
    return StubConsensusSigner(tag.ljust(20, b"\x00"))


def fresh_session(n: int, config: ConsensusConfig, liveness=False) -> ConsensusSession:
    request = CreateProposalRequest(
        name="Test",
        payload=b"",
        proposal_owner=signer(b"owner").identity(),
        expected_voters_count=n,
        expiration_timestamp=60,
        liveness_criteria_yes=liveness,
    )
    proposal = request.into_proposal(NOW)
    return ConsensusSession._new(proposal, config, NOW)


class TestRoundLimits:
    def test_enforce_max_rounds_gossipsub(self):
        """reference: src/session.rs:426-469 — 4 votes all live in round 2."""
        session = fresh_session(4, ConsensusConfig.gossipsub())
        for i, choice in enumerate([True, False, True, True]):
            vote = build_vote(session.proposal, choice, signer(b"v%d" % i), NOW)
            session.add_vote(vote, NOW)
            assert session.proposal.round == 2
        assert len(session.votes) == 4

    def test_enforce_max_rounds_p2p(self):
        """reference: src/session.rs:471-524 — n=5: cap ceil(2n/3)=4 votes,
        5th vote fails with MaxRoundsExceeded."""
        session = fresh_session(5, ConsensusConfig.p2p())
        for i, choice in enumerate([True, False, True, True]):
            vote = build_vote(session.proposal, choice, signer(b"v%d" % i), NOW)
            session.add_vote(vote, NOW)
            assert session.proposal.round == i + 2
            assert len(session.votes) == i + 1
        vote5 = build_vote(session.proposal, True, signer(b"v5"), NOW)
        with pytest.raises(MaxRoundsExceeded):
            session.add_vote(vote5, NOW)
        assert session.state.is_failed

    def test_explicit_max_rounds_overrides_dynamic(self):
        """reference: src/session.rs:546-552"""
        explicit = ConsensusConfig(
            consensus_threshold=2.0 / 3.0,
            consensus_timeout=60.0,
            max_rounds=7,
            use_gossipsub_rounds=False,
            liveness_criteria=True,
        )
        assert explicit.max_round_limit(100) == 7

    def test_huge_vote_count_rejected(self):
        """reference: src/session.rs:639-668 — a batch larger than u32::MAX
        votes must be rejected by round-limit checks."""
        session = fresh_session(1, ConsensusConfig.p2p())
        with pytest.raises(MaxRoundsExceeded):
            session._check_round_limit(U32_MAX + 1)
        assert session.state.is_failed

    def test_update_round_saturates_at_u32_max(self):
        """reference: src/session.rs:670-699"""
        session = fresh_session(U32_MAX, ConsensusConfig.p2p())
        start = session.proposal.round
        session._update_round(U32_MAX)
        assert session.proposal.round > start
        assert session.proposal.round == U32_MAX

    def test_gossipsub_zero_votes_round_projection(self):
        """reference: src/session.rs:630-633 — vote_count=0 at round 1 passes."""
        session = fresh_session(4, ConsensusConfig.gossipsub())
        session._check_round_limit(0)
        assert session.proposal.round == 1


class TestConfigBuilder:
    def test_builder_and_getters(self):
        """reference: src/session.rs:526-553"""
        cfg = (
            ConsensusConfig.gossipsub()
            .with_threshold(0.75)
            .with_timeout(42.0)
            .with_liveness_criteria(False)
        )
        assert cfg.consensus_threshold == 0.75
        assert cfg.consensus_timeout == 42.0
        assert cfg.liveness_criteria is False

        with pytest.raises(InvalidConsensusThreshold):
            ConsensusConfig.gossipsub().with_threshold(1.1)
        with pytest.raises(InvalidTimeout):
            ConsensusConfig.gossipsub().with_timeout(0)

    def test_presets(self):
        g = ConsensusConfig.gossipsub()
        assert g.max_rounds == 2 and g.use_gossipsub_rounds
        p = ConsensusConfig.p2p()
        assert p.max_rounds == 0 and not p.use_gossipsub_rounds
        # dynamic limit for p2p
        assert p.max_round_limit(9) == 6


class TestStateMachine:
    def test_failed_session_rejects_votes(self):
        """reference: src/session.rs:555-592"""
        session = fresh_session(3, ConsensusConfig.gossipsub(), liveness=True)
        session.state = ConsensusState.failed()
        vote = build_vote(session.proposal, True, signer(b"a"), NOW)
        with pytest.raises(SessionNotActive):
            session.add_vote(vote, NOW)

    def test_finalized_session_reports_reached(self):
        session = fresh_session(3, ConsensusConfig.gossipsub(), liveness=True)
        session.state = ConsensusState.reached(True)
        vote = build_vote(session.proposal, True, signer(b"a"), NOW)
        transition = session.add_vote(vote, NOW)
        assert transition.is_reached and transition.reached is True
        assert len(session.votes) == 0  # not inserted

    def test_initialize_non_active_rejected(self):
        """reference: src/session.rs:594-637"""
        session = fresh_session(4, ConsensusConfig.gossipsub(), liveness=True)
        session.state = ConsensusState.failed()
        with pytest.raises(SessionNotActive):
            session.initialize_with_votes(
                [],
                StubConsensusSigner,
                session.proposal.expiration_timestamp,
                session.proposal.timestamp,
                NOW,
            )

    def test_initialize_duplicate_owner_rejected(self):
        session = fresh_session(4, ConsensusConfig.gossipsub(), liveness=True)
        s = signer(b"dup")
        v1 = build_vote(session.proposal, True, s, NOW)
        v2 = build_vote(session.proposal, False, s, NOW)
        with pytest.raises(DuplicateVote):
            session.initialize_with_votes(
                [v1, v2],
                StubConsensusSigner,
                session.proposal.expiration_timestamp,
                session.proposal.timestamp,
                NOW,
            )

    def test_initialize_batch_larger_than_n_fails_session(self):
        """reference: src/session.rs:277-282"""
        session = fresh_session(2, ConsensusConfig.gossipsub(), liveness=True)
        votes = []
        proposal = session.proposal.clone()
        for i in range(3):
            v = build_vote(proposal, True, signer(b"v%d" % i), NOW)
            proposal.votes.append(v)
            votes.append(v)
        with pytest.raises(MaxRoundsExceeded):
            session.initialize_with_votes(
                votes,
                StubConsensusSigner,
                session.proposal.expiration_timestamp,
                session.proposal.timestamp,
                NOW,
            )
        assert session.state.is_failed

    def test_consensus_reached_via_add_vote(self):
        session = fresh_session(3, ConsensusConfig.gossipsub(), liveness=True)
        v1 = build_vote(session.proposal, True, signer(b"a"), NOW)
        t1 = session.add_vote(v1, NOW)
        assert not t1.is_reached
        v2 = build_vote(session.proposal, True, signer(b"b"), NOW)
        t2 = session.add_vote(v2, NOW)
        # 2 YES of n=3: quorum 2 met, yes_weight=2+1(silent,liveness)=3 > no=0
        assert t2.is_reached and t2.reached is True
        assert session.get_consensus_result() is True

    def test_from_proposal_replays_votes(self):
        """reference: src/session.rs:198-221 — embedded votes replayed from a
        clean round-1 state."""
        origin = fresh_session(3, ConsensusConfig.gossipsub(), liveness=True)
        for tag in (b"a", b"b"):
            v = build_vote(origin.proposal, True, signer(tag), NOW)
            origin.add_vote(v, NOW)

        session, transition = ConsensusSession.from_proposal(
            origin.proposal.clone(), StubConsensusSigner, ConsensusConfig.gossipsub(), NOW
        )
        assert transition.is_reached and transition.reached is True
        assert len(session.votes) == 2
        assert session.proposal.round == 2
