"""Scope config builder/presets/validation (reference: tests/scope_config_tests.rs)."""

import pytest

from hashgraph_tpu import NetworkType, ScopeConfig, ScopeConfigBuilder
from hashgraph_tpu.errors import (
    InvalidConsensusThreshold,
    InvalidMaxRounds,
    InvalidTimeout,
)
from hashgraph_tpu.session import ConsensusConfig

from common import make_service

SCOPE = "scope_config_scope"


class TestBuilder:
    def test_defaults(self):
        config = ScopeConfigBuilder().build()
        assert config.network_type == NetworkType.GOSSIPSUB
        assert config.default_consensus_threshold == 2.0 / 3.0
        assert config.default_timeout == 60.0
        assert config.default_liveness_criteria_yes is True
        assert config.max_rounds_override is None

    def test_fluent_fields(self):
        config = (
            ScopeConfigBuilder()
            .with_network_type(NetworkType.P2P)
            .with_threshold(0.8)
            .with_timeout(90.0)
            .with_liveness_criteria(False)
            .with_max_rounds(5)
            .build()
        )
        assert config.network_type == NetworkType.P2P
        assert config.default_consensus_threshold == 0.8
        assert config.default_timeout == 90.0
        assert config.default_liveness_criteria_yes is False
        assert config.max_rounds_override == 5

    def test_presets(self):
        p2p = ScopeConfigBuilder().p2p_preset().build()
        assert p2p.network_type == NetworkType.P2P
        gossip = ScopeConfigBuilder().gossipsub_preset().build()
        assert gossip.network_type == NetworkType.GOSSIPSUB

        strict = ScopeConfigBuilder().strict_consensus().build()
        assert strict.default_consensus_threshold == 0.9
        fast = ScopeConfigBuilder().fast_consensus().build()
        assert fast.default_consensus_threshold == 0.6
        assert fast.default_timeout == 30.0

    def test_network_defaults_preserve_liveness_and_override(self):
        """reference: src/scope_config.rs:173-187 — with_network_defaults resets
        network/threshold/timeout but not liveness or max_rounds_override."""
        config = (
            ScopeConfigBuilder()
            .with_liveness_criteria(False)
            .with_max_rounds(9)
            .with_network_defaults(NetworkType.P2P)
            .build()
        )
        assert config.network_type == NetworkType.P2P
        assert config.default_liveness_criteria_yes is False
        assert config.max_rounds_override == 9

    def test_validation(self):
        with pytest.raises(InvalidConsensusThreshold):
            ScopeConfigBuilder().with_threshold(1.5).build()
        with pytest.raises(InvalidTimeout):
            ScopeConfigBuilder().with_timeout(0).build()
        # Some(0) override illegal for Gossipsub, legal for P2P.
        with pytest.raises(InvalidMaxRounds):
            ScopeConfigBuilder().with_max_rounds(0).build()
        ScopeConfigBuilder().with_network_type(NetworkType.P2P).with_max_rounds(0).build()

    def test_from_existing(self):
        base = ScopeConfig(default_consensus_threshold=0.7)
        updated = ScopeConfigBuilder.from_existing(base).with_timeout(10.0).build()
        assert updated.default_consensus_threshold == 0.7
        assert updated.default_timeout == 10.0
        # Builder mutation does not alias the original.
        assert base.default_timeout == 60.0


class TestServiceScopeApi:
    """reference: tests/scope_config_tests.rs init/update via the service."""

    def test_initialize_and_update(self):
        service = make_service()
        service.scope(SCOPE).with_network_type(NetworkType.P2P).with_threshold(
            0.75
        ).with_timeout(120.0).initialize()

        config = service.storage().get_scope_config(SCOPE)
        assert config.network_type == NetworkType.P2P
        assert config.default_consensus_threshold == 0.75

        # Update a single field: existing values are the builder's base.
        service.scope(SCOPE).with_threshold(0.8).update()
        config = service.storage().get_scope_config(SCOPE)
        assert config.default_consensus_threshold == 0.8
        assert config.network_type == NetworkType.P2P  # preserved
        assert config.default_timeout == 120.0  # preserved

    def test_override_timeout_preserved_on_profile_update(self):
        """reference: tests/scope_config_tests.rs:238-266"""
        service = make_service()
        service.scope(SCOPE).with_timeout(300.0).with_max_rounds(4).initialize()
        service.scope(SCOPE).strict_consensus().update()
        config = service.storage().get_scope_config(SCOPE)
        assert config.default_consensus_threshold == 0.9
        assert config.default_timeout == 300.0
        assert config.max_rounds_override == 4

    def test_get_config_reflects_pending_builder(self):
        service = make_service()
        wrapper = service.scope(SCOPE).with_threshold(0.77)
        assert wrapper.get_config().default_consensus_threshold == 0.77
        # not persisted until initialize()
        assert service.storage().get_scope_config(SCOPE) is None

    def test_scope_config_to_consensus_config_mapping(self):
        """reference: src/session.rs:52-68"""
        gossip = ConsensusConfig.from_scope_config(
            ScopeConfig(network_type=NetworkType.GOSSIPSUB, max_rounds_override=None)
        )
        assert gossip.max_rounds == 2 and gossip.use_gossipsub_rounds
        p2p = ConsensusConfig.from_scope_config(
            ScopeConfig(network_type=NetworkType.P2P, max_rounds_override=None)
        )
        assert p2p.max_rounds == 0 and not p2p.use_gossipsub_rounds
        override = ConsensusConfig.from_scope_config(
            ScopeConfig(network_type=NetworkType.P2P, max_rounds_override=7)
        )
        assert override.max_rounds == 7
