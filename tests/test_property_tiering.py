"""Hypothesis search over the tiered-vs-untier'd identity op space.

The script runner (and the always-on seeded trials) live in
tests/test_tiering.py; this file lets hypothesis hunt the op space —
shrinking to a minimal counterexample — wherever the dev extra is
installed (importorskips cleanly elsewhere, like the other property
suites).
"""

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from test_tiering import run_identity_script

_op = st.one_of(
    st.tuples(st.just("create"), st.integers(1, 4)),
    st.tuples(
        st.just("vote"),
        st.integers(0, 7),  # session pick (mod live)
        st.integers(0, 3),  # signer
        st.booleans(),
    ),
    st.tuples(st.just("timeout"), st.integers(0, 7)),
    st.tuples(st.just("sweep"), st.integers(1, 30)),
    st.tuples(st.just("demote"), st.integers(0, 7)),
    st.tuples(st.just("demote_all")),
)


@settings(max_examples=20, deadline=None)
@given(script=st.lists(_op, min_size=3, max_size=20))
def test_tiered_untiered_decision_identity(script):
    run_identity_script(script)
