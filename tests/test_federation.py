"""Federated multi-host fleet, in-process: two FleetGroups over real
TCP loopback — remote vote routing over the gossip fabric, cross-host
tallies on the fabric path, and live shard migration under traffic with
the typed retry-after window.

(The multi-PROCESS topology — separate OS processes per host — is
``bench.py fleet --hosts 2 --smoke``, the federation-smoke CI job;
these tests exercise the same code with both groups in one process.)"""

import threading
import time

import pytest

from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner, build_vote
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.parallel.federation import (
    FederationPlacement,
    FleetGroup,
    migrate_shard,
)
from hashgraph_tpu.parallel.fleet import ShardMigratingError

NOW = 1_700_000_000
OK = int(StatusCode.OK)
ALREADY = int(StatusCode.ALREADY_REACHED)


def _build_federation(wal_root):
    placement = FederationPlacement.uniform(["h0", "h1"], 2)
    groups = {}
    for host in ("h0", "h1"):
        groups[host] = FleetGroup(
            host,
            lambda k: StubConsensusSigner(bytes([k + 1]) * 20),
            placement=placement,
            wal_root=wal_root,
            capacity_per_shard=64,
            voter_capacity=8,
        )
        groups[host].start()
    for a in groups:
        for b in groups:
            if a != b:
                groups[a].connect(b, *groups[b].address, groups[b].peer_id)
    return placement, groups


# Module-scoped: building two FleetGroups compiles jax kernels, so the
# read-only / freeze-and-abort tests share one topology (distinct scope
# tags keep them independent). Tests that CHANGE the topology (a real
# migration) take the fresh fixture below.
@pytest.fixture(scope="module")
def federation(tmp_path_factory):
    placement, groups = _build_federation(
        str(tmp_path_factory.mktemp("federation"))
    )
    try:
        yield placement, groups
    finally:
        for group in groups.values():
            group.close()


@pytest.fixture()
def fresh_federation(tmp_path):
    placement, groups = _build_federation(str(tmp_path))
    try:
        yield placement, groups
    finally:
        for group in groups.values():
            group.close()


def scope_owned_by(placement, host, tag="s"):
    return next(
        f"{tag}{i}" for i in range(1000)
        if placement.owner(f"{tag}{i}")[0] == host
    )


def make_session(placement, groups, scope, voters=3):
    """Create a proposal on the owner host, pin, and return (proposal,
    ``voters`` chained signed votes)."""
    host, shard = placement.owner(scope)
    request = CreateProposalRequest(
        name="p", payload=b"", proposal_owner=b"o" * 20,
        expected_voters_count=voters, expiration_timestamp=3600,
        liveness_criteria_yes=True,
    )
    proposal = groups[host].adapter.create_proposal(scope, request, NOW)
    placement.pin(scope, shard)
    votes = []
    for i in range(voters):
        vote = build_vote(
            proposal, True, StubConsensusSigner(bytes([50 + i]) * 20), NOW + 1
        )
        proposal.votes.append(vote)
        votes.append(vote)
    return proposal, votes


def test_remote_votes_ride_the_fabric(federation):
    """Votes submitted on the NON-owning host land on the owner over a
    coalesced OP_VOTE_BATCH frame — not SESSION_NOT_FOUND."""
    placement, groups = federation
    scope = scope_owned_by(placement, "h1")
    proposal, votes = make_session(placement, groups, scope)
    statuses = groups["h0"].ingest_votes(
        [(scope, v) for v in votes[:2]], NOW + 2
    )
    assert (statuses == OK).all(), statuses
    # 2/3 quorum: decided on the owner.
    assert (
        groups["h1"].adapter.get_consensus_result(
            scope, proposal.proposal_id
        )
        is True
    )
    # Mixed local+remote batch in one call, statuses in input order.
    local_scope = scope_owned_by(placement, "h0", tag="loc")
    local_prop, local_votes = make_session(placement, groups, local_scope)
    mixed = [(scope, votes[2]), (local_scope, local_votes[0]),
             (local_scope, local_votes[1])]
    statuses = groups["h0"].ingest_votes(mixed, NOW + 3)
    assert statuses[0] == ALREADY  # decided session absorbs
    assert statuses[1] == OK and statuses[2] == OK, statuses


def test_remote_statuses_align_on_interleaved_scopes(federation):
    """Two remote scopes interleaved in one call: the frame groups rows
    per scope (reordering them), so statuses must map back through the
    frame order — each row's status describes ITS vote. A bad vote
    placed between good ones is the discriminator."""
    placement, groups = federation
    s_a = scope_owned_by(placement, "h1", tag="ila")
    s_b = scope_owned_by(placement, "h1", tag="ilb")
    _pa, votes_a = make_session(placement, groups, s_a)
    _pb, votes_b = make_session(placement, groups, s_b)
    # B's SECOND vote without its first: a dangling chain link the
    # engine rejects (RECEIVED_HASH_MISMATCH) — in input position 1,
    # but in frame position 2 (after both A rows).
    items = [(s_a, votes_a[0]), (s_b, votes_b[1]), (s_a, votes_a[1])]
    statuses = groups["h0"].ingest_votes(items, NOW + 2)
    assert statuses[0] == OK, statuses
    assert statuses[1] == int(StatusCode.RECEIVED_HASH_MISMATCH), statuses
    assert statuses[2] == OK, statuses


def test_deliver_proposals_routes_remotely(federation):
    placement, groups = federation
    scope = scope_owned_by(placement, "h1", tag="dlv")
    proposal, _votes = make_session(placement, groups, scope)
    # Deliver the full chain from the non-owner: extends the owner's
    # empty chain via the watermark path (one OP_DELIVER_PROPOSALS
    # frame over the fabric).
    codes = groups["h0"].deliver_proposals([(scope, proposal)], NOW + 2)
    assert codes[0] in (OK, int(StatusCode.PROPOSAL_ALREADY_EXIST)), codes
    assert (
        groups["h1"].adapter.get_consensus_result(
            scope, proposal.proposal_id
        )
        is True
    )


def test_federated_state_counts_fabric_path(federation):
    """Cross-host tallies on the OP_FLEET_TALLY fabric arm (this box has
    no cross-process collectives — tally_path() says so)."""
    from hashgraph_tpu.parallel.federation import tally_path

    placement, groups = federation
    assert tally_path() == "fabric"
    from hashgraph_tpu.ops.decide import STATE_ACTIVE

    before = groups["h0"].federated_state_counts()
    for host in ("h0", "h1"):
        scope = scope_owned_by(placement, host, tag=f"tly-{host}-")
        make_session(placement, groups, scope)
    counts0 = groups["h0"].federated_state_counts()
    counts1 = groups["h1"].federated_state_counts()
    assert counts0 == counts1  # both sum the same federation
    delta = counts0.get(STATE_ACTIVE, 0) - before.get(STATE_ACTIVE, 0)
    assert delta == 2, (before, counts0)
    # The federation's total slot space: 2 hosts x 2 shards x 64.
    assert sum(counts0.values()) == 4 * 64, counts0


def test_fleet_tally_opcode_over_bridge(federation):
    from hashgraph_tpu.bridge.client import BridgeClient

    placement, groups = federation
    with BridgeClient(*groups["h0"].address) as client:
        counts = client.fleet_tally(groups["h0"].peer_id)
    # One host's whole local fleet: 2 shards x 64 slots.
    assert sum(counts.values()) == 2 * 64, counts


def test_migrating_shard_raises_typed_with_retry_after(federation):
    placement, groups = federation
    scope = scope_owned_by(placement, "h1", tag="frz")
    _proposal, votes = make_session(placement, groups, scope)
    _host, shard = placement.owner(scope)
    # Freeze BOTH sides the orchestrator freezes: the placement (drivers
    # consult it) and the owning fleet (the wire refuses typed).
    placement.begin_migration(shard, retry_after=0.25)
    groups["h1"].fleet.begin_migration(shard, retry_after=0.25)
    try:
        with pytest.raises(ShardMigratingError) as excinfo:
            groups["h0"].ingest_votes([(scope, votes[0])], NOW + 2)
        assert excinfo.value.retry_after == 0.25
        assert excinfo.value.shard_id == shard
        # Local routes on the owner refuse the same way.
        with pytest.raises(ShardMigratingError):
            groups["h1"].ingest_votes([(scope, votes[0])], NOW + 2)
    finally:
        placement.abort_migration(shard)
        groups["h1"].fleet.end_migration(shard)
    # The freeze lifted: the held vote lands.
    statuses = groups["h0"].ingest_votes([(scope, votes[0])], NOW + 3)
    assert statuses[0] == OK, statuses


def test_wire_migrating_status_crosses_the_bridge(federation):
    """The typed refusal survives the wire: a remote sender's
    OP_VOTE_BATCH frame comes back STATUS_SHARD_MIGRATING (246) when
    the owner froze AFTER the sender's placement read."""
    from hashgraph_tpu.bridge import protocol as P
    from hashgraph_tpu.bridge.client import BridgeClient, BridgeError

    placement, groups = federation
    scope = scope_owned_by(placement, "h1", tag="wire")
    _proposal, votes = make_session(placement, groups, scope)
    _host, shard = placement.owner(scope)
    groups["h1"].fleet.begin_migration(shard, retry_after=0.5)
    try:
        with BridgeClient(*groups["h1"].address) as client:
            payload = P.encode_vote_batch(
                NOW + 2,
                [(groups["h1"].peer_id, scope, [votes[0].encode()])],
            )
            with pytest.raises(BridgeError) as excinfo:
                client._call(P.OP_VOTE_BATCH, payload)
            assert excinfo.value.status == P.STATUS_SHARD_MIGRATING
    finally:
        groups["h1"].fleet.end_migration(shard)


def test_live_migration_under_traffic(fresh_federation):
    """The tentpole end to end, in process: sustained ingest with a
    typed-retry loop while the scope's shard re-homes h1 -> h0.
    Zero lost votes, source==destination fingerprints (asserted inside
    migrate_shard), atomic flip, migration metrics + flight events, and
    the session keeps serving."""
    from hashgraph_tpu.obs import (
        FEDERATION_MIGRATION_SECONDS,
        FEDERATION_MIGRATIONS_TOTAL,
        registry,
    )

    placement, groups = fresh_federation
    migrations0 = registry.counter(FEDERATION_MIGRATIONS_TOTAL).value
    seconds0 = registry.histogram(FEDERATION_MIGRATION_SECONDS).count
    scope = scope_owned_by(placement, "h1", tag="live")
    # 24 chained votes against a quorum of EXACTLY 24 (ceil(2*36/3)):
    # the last vote is the deciding one, so `result is True` proves
    # every single vote survived the migration — and no vote ever links
    # past an absorbed post-decision vote (which would be a dangling
    # chain by protocol rule, not a migration artifact).
    host, shard = placement.owner(scope)
    request = CreateProposalRequest(
        name="p", payload=b"", proposal_owner=b"o" * 20,
        expected_voters_count=36, expiration_timestamp=3600,
        liveness_criteria_yes=True,
    )
    proposal = groups[host].adapter.create_proposal(scope, request, NOW)
    placement.pin(scope, shard)
    votes = []
    for i in range(24):
        vote = build_vote(
            proposal, True, StubConsensusSigner(bytes([50 + i]) * 20),
            NOW + 1,
        )
        proposal.votes.append(vote)
        votes.append(vote)

    applied = []
    errors = []

    def traffic():
        try:
            for vote in votes:
                while True:  # the retry-after loop the error prescribes
                    try:
                        statuses = groups["h0"].ingest_votes(
                            [(scope, vote)], NOW + 2
                        )
                        break
                    except ShardMigratingError as exc:
                        time.sleep(min(exc.retry_after, 0.05))
                assert statuses[0] in (OK, ALREADY), statuses
                applied.append(int(statuses[0]))
        except BaseException as exc:  # surfaced by the join below
            errors.append(exc)

    thread = threading.Thread(target=traffic)
    thread.start()
    time.sleep(0.05)  # let some votes land pre-migration
    report = migrate_shard(
        placement, groups, shard, "h0", retry_after=0.05
    )
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert not errors, errors
    assert report["from"] == "h1" and report["to"] == "h0"
    assert report["sessions"] >= 1
    assert placement.owner(scope) == ("h0", shard)
    assert shard in groups["h0"].fleet.shard_ids
    assert shard not in groups["h1"].fleet.shard_ids
    # ZERO LOST VOTES: all 24 landed as plain acks across freeze+flip.
    assert len(applied) == 24 and all(s == OK for s in applied), applied
    # The migrated session decided on its new home AT THE LAST VOTE:
    # True iff nothing was lost across the migration.
    result = groups["h0"].adapter.get_consensus_result(
        scope, proposal.proposal_id
    )
    assert result is True, (result, applied)
    # One migration, counted and timed.
    assert (
        registry.counter(FEDERATION_MIGRATIONS_TOTAL).value
        == migrations0 + 1
    )
    assert (
        registry.histogram(FEDERATION_MIGRATION_SECONDS).count
        == seconds0 + 1
    )
    # Drain h1 COMPLETELY (its last shard migrates too — the
    # decommission flow): the emptied host keeps serving the wire, and
    # new scopes rendezvous only onto hosts that home shards.
    last = placement.shards_of("h1")[0]
    migrate_shard(placement, groups, last, "h0")
    assert placement.shards_of("h1") == []
    assert groups["h1"].fleet.n_shards == 0
    for i in range(16):
        assert placement.owner(f"post-drain-{i}")[0] == "h0"


def test_migrate_shard_unknown_target_leaves_topology_intact(federation):
    placement, groups = federation
    scope = scope_owned_by(placement, "h1", tag="abrt")
    _proposal, votes = make_session(placement, groups, scope)
    _host, shard = placement.owner(scope)
    with pytest.raises(KeyError):
        migrate_shard(placement, groups, shard, "nope")
    # Rolled back: not migrating, still owned and serving on h1.
    assert not placement.migrating(shard)
    assert placement.host_of(shard) == "h1"
    statuses = groups["h0"].ingest_votes([(scope, votes[0])], NOW + 2)
    assert statuses[0] == OK, statuses


def test_adapter_columnar_wire_multi_scope(federation):
    """A multi-scope OP_VOTE_BATCH frame through the host's zero-copy
    columnar ingest: rows split per owning shard (columnar.pack_rows)
    and every status lands in flattened frame order."""
    from hashgraph_tpu.bridge import protocol as P
    from hashgraph_tpu.bridge.client import BridgeClient, parse_status_list

    placement, groups = federation
    sessions = []
    for i in range(4):
        scope = scope_owned_by(placement, "h0", tag=f"col{i}-")
        _proposal, votes = make_session(placement, groups, scope)
        sessions.append((scope, votes))
    frame_groups = [
        (groups["h0"].peer_id, scope, [v.encode() for v in votes[:2]])
        for scope, votes in sessions
    ]
    payload = P.encode_vote_batch(NOW + 2, frame_groups)
    with BridgeClient(*groups["h0"].address) as client:
        statuses = parse_status_list(client._call(P.OP_VOTE_BATCH, payload))
    assert statuses == [OK] * 8, statuses
    for scope, _votes in sessions:
        assert (
            groups["h0"].adapter.get_consensus_result(
                scope, _votes[0].proposal_id
            )
            is True
        )


def test_host_fingerprint_covers_all_shards(federation):
    """The adapter's state_fingerprint digests the union of the shards'
    canonical frames: adding a session on EITHER shard changes it."""
    placement, groups = federation
    before = groups["h0"].state_fingerprint()
    scope = scope_owned_by(placement, "h0", tag="fpr")
    make_session(placement, groups, scope)
    assert groups["h0"].state_fingerprint() != before
