"""Property-based wire-codec fuzz: round-trips and decoder robustness."""

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from hashgraph_tpu.wire import Proposal, Vote

U32 = st.integers(min_value=0, max_value=0xFFFFFFFF)
U64 = st.integers(min_value=0, max_value=0xFFFFFFFFFFFFFFFF)
BYTES = st.binary(max_size=80)

votes = st.builds(
    Vote,
    vote_id=U32,
    vote_owner=BYTES,
    proposal_id=U32,
    timestamp=U64,
    vote=st.booleans(),
    parent_hash=BYTES,
    received_hash=BYTES,
    vote_hash=BYTES,
    signature=BYTES,
)

proposals = st.builds(
    Proposal,
    name=st.text(max_size=40),
    payload=BYTES,
    proposal_id=U32,
    proposal_owner=BYTES,
    votes=st.lists(votes, max_size=5),
    expected_voters_count=U32,
    round=U32,
    timestamp=U64,
    expiration_timestamp=U64,
    liveness_criteria_yes=st.booleans(),
)


@settings(max_examples=300, deadline=None)
@given(vote=votes)
def test_vote_roundtrip(vote):
    assert Vote.decode(vote.encode()) == vote


@settings(max_examples=150, deadline=None)
@given(proposal=proposals)
def test_proposal_roundtrip(proposal):
    decoded = Proposal.decode(proposal.encode())
    assert decoded == proposal
    # Re-encoding is stable (canonical form).
    assert decoded.encode() == proposal.encode()


@settings(max_examples=300, deadline=None)
@given(junk=st.binary(max_size=120))
def test_decoder_never_crashes_unexpectedly(junk):
    """Arbitrary bytes either decode or raise ValueError — never anything
    else (no hangs, no index errors)."""
    for cls in (Vote, Proposal):
        try:
            cls.decode(junk)
        except ValueError:
            pass


# ── Trace-context backward compatibility ───────────────────────────────

from hashgraph_tpu.obs.trace import (  # noqa: E402
    TraceContext,
    attach_trace,
    extract_trace,
)

contexts = st.builds(
    TraceContext,
    trace_id=st.binary(min_size=16, max_size=16),
    span_id=st.binary(min_size=8, max_size=8),
    flags=st.integers(min_value=0, max_value=255),
)


@settings(max_examples=200, deadline=None)
@given(vote=votes, ctx=contexts)
def test_vote_with_attached_trace_decodes_identically(vote, ctx):
    """The gossip trace field is invisible to decoders (old peers see the
    exact same Vote) and recoverable by new peers."""
    raw = attach_trace(vote.encode(), ctx)
    assert Vote.decode(raw) == vote
    assert extract_trace(raw) == ctx
    # Re-encoding the decoded message drops the unknown field — the
    # canonical form (and any signature over it) is unchanged.
    assert Vote.decode(raw).encode() == vote.encode()


@settings(max_examples=100, deadline=None)
@given(proposal=proposals, ctx=contexts)
def test_proposal_with_attached_trace_decodes_identically(proposal, ctx):
    raw = attach_trace(proposal.encode(), ctx)
    assert Proposal.decode(raw) == proposal
    assert extract_trace(raw) == ctx


@settings(max_examples=300, deadline=None)
@given(junk=st.binary(max_size=120))
def test_extract_trace_never_raises(junk):
    """extract_trace consumes untrusted gossip bytes: absent/malformed
    contexts yield None, never an exception."""
    assert extract_trace(junk) is None or isinstance(
        extract_trace(junk), TraceContext
    )
