"""Property-based wire-codec fuzz: round-trips and decoder robustness."""

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from hashgraph_tpu.wire import Proposal, Vote

U32 = st.integers(min_value=0, max_value=0xFFFFFFFF)
U64 = st.integers(min_value=0, max_value=0xFFFFFFFFFFFFFFFF)
BYTES = st.binary(max_size=80)

votes = st.builds(
    Vote,
    vote_id=U32,
    vote_owner=BYTES,
    proposal_id=U32,
    timestamp=U64,
    vote=st.booleans(),
    parent_hash=BYTES,
    received_hash=BYTES,
    vote_hash=BYTES,
    signature=BYTES,
)

proposals = st.builds(
    Proposal,
    name=st.text(max_size=40),
    payload=BYTES,
    proposal_id=U32,
    proposal_owner=BYTES,
    votes=st.lists(votes, max_size=5),
    expected_voters_count=U32,
    round=U32,
    timestamp=U64,
    expiration_timestamp=U64,
    liveness_criteria_yes=st.booleans(),
)


@settings(max_examples=300, deadline=None)
@given(vote=votes)
def test_vote_roundtrip(vote):
    assert Vote.decode(vote.encode()) == vote


@settings(max_examples=150, deadline=None)
@given(proposal=proposals)
def test_proposal_roundtrip(proposal):
    decoded = Proposal.decode(proposal.encode())
    assert decoded == proposal
    # Re-encoding is stable (canonical form).
    assert decoded.encode() == proposal.encode()


@settings(max_examples=300, deadline=None)
@given(junk=st.binary(max_size=120))
def test_decoder_never_crashes_unexpectedly(junk):
    """Arbitrary bytes either decode or raise ValueError — never anything
    else (no hangs, no index errors)."""
    for cls in (Vote, Proposal):
        try:
            cls.decode(junk)
        except ValueError:
            pass


# ── Trace-context backward compatibility ───────────────────────────────

from hashgraph_tpu.obs.trace import (  # noqa: E402
    TraceContext,
    attach_trace,
    extract_trace,
)

contexts = st.builds(
    TraceContext,
    trace_id=st.binary(min_size=16, max_size=16),
    span_id=st.binary(min_size=8, max_size=8),
    flags=st.integers(min_value=0, max_value=255),
)


@settings(max_examples=200, deadline=None)
@given(vote=votes, ctx=contexts)
def test_vote_with_attached_trace_decodes_identically(vote, ctx):
    """The gossip trace field is invisible to decoders (old peers see the
    exact same Vote) and recoverable by new peers."""
    raw = attach_trace(vote.encode(), ctx)
    assert Vote.decode(raw) == vote
    assert extract_trace(raw) == ctx
    # Re-encoding the decoded message drops the unknown field — the
    # canonical form (and any signature over it) is unchanged.
    assert Vote.decode(raw).encode() == vote.encode()


@settings(max_examples=100, deadline=None)
@given(proposal=proposals, ctx=contexts)
def test_proposal_with_attached_trace_decodes_identically(proposal, ctx):
    raw = attach_trace(proposal.encode(), ctx)
    assert Proposal.decode(raw) == proposal
    assert extract_trace(raw) == ctx


@settings(max_examples=300, deadline=None)
@given(junk=st.binary(max_size=120))
def test_extract_trace_never_raises(junk):
    """extract_trace consumes untrusted gossip bytes: absent/malformed
    contexts yield None, never an exception."""
    assert extract_trace(junk) is None or isinstance(
        extract_trace(junk), TraceContext
    )


# ── Columnar OP_VOTE_BATCH decode: fuzz vs the object-path oracle ──────
#
# Two embedded bridge servers receive byte-identical frame sequences —
# one with the zero-copy columnar wire path, one forced onto the
# per-vote object decoder — and every response must match byte for byte:
# malformed length columns, overflowing counts, truncated vote-bytes
# regions, junk rows, valid signed chains, all of it.

from hashgraph_tpu import build_vote  # noqa: E402
from hashgraph_tpu.bridge import protocol as P  # noqa: E402
from hashgraph_tpu.bridge.server import BridgeServer  # noqa: E402
from hashgraph_tpu.signing.stub import StubConsensusSigner  # noqa: E402

_NOW = 1_700_000_000


class _Oracle:
    def __init__(self):
        self.pair = []
        for wire_columnar in (True, False):
            server = BridgeServer(
                signer_factory=StubConsensusSigner,
                capacity=512,
                voter_capacity=16,
                wire_columnar=wire_columnar,
            )
            server.start_embedded()
            self.pair.append(server)
        add = P.u8(32) + b"\x11" * 32
        self.peer_id = P.Cursor(
            self.dispatch(P.OP_ADD_PEER, add)[1]
        ).u32()
        self.scope_seq = 0

    def dispatch(self, opcode, payload):
        a = self.pair[0].dispatch_frame(opcode, payload)
        b = self.pair[1].dispatch_frame(opcode, payload)
        assert a == b, (
            f"columnar/object divergence on opcode {opcode}: {a} != {b}"
        )
        return a

    def fresh_session(self):
        """A fresh scope + delivered proposal + its signed chain rows."""
        self.scope_seq += 1
        scope = f"fz-{self.scope_seq}"
        proposal = Proposal(
            name=scope,
            payload=b"x",
            proposal_id=self.scope_seq,
            proposal_owner=b"\x11" * 20,
            expected_voters_count=12,
            timestamp=_NOW,
            expiration_timestamp=_NOW + 3_600,
            liveness_criteria_yes=True,
        )
        self.dispatch(
            P.OP_PROCESS_PROPOSAL,
            P.u32(self.peer_id) + P.string(scope) + P.u64(_NOW)
            + P.blob(proposal.encode()),
        )
        rows = []
        for i in range(1, 7):
            vote = build_vote(
                proposal, True, StubConsensusSigner(bytes([i]) * 20), _NOW + 1
            )
            proposal.votes.append(vote)
            rows.append(vote.encode())
        return scope, rows


_oracle_holder: "list[_Oracle]" = []


def _oracle() -> _Oracle:
    if not _oracle_holder:
        _oracle_holder.append(_Oracle())
    return _oracle_holder[0]


row_mutations = st.sampled_from(
    ["keep", "flip", "truncate", "junk", "empty"]
)


@settings(max_examples=40, deadline=None)
@given(
    plan=st.lists(row_mutations, min_size=1, max_size=6),
    junk_seed=st.integers(min_value=0, max_value=2**31),
    data=st.data(),
)
def test_vote_batch_columnar_matches_object_path(plan, junk_seed, data):
    """Row-level fuzz: every mutated/valid/junk row mix produces
    byte-identical per-row statuses on both server paths, frame after
    frame on one session (cross-frame guard state included)."""
    oracle = _oracle()
    scope, rows = oracle.fresh_session()
    import random as _random

    rng = _random.Random(junk_seed)
    frame_rows = []
    for kind, row in zip(plan, rows):
        if kind == "keep":
            frame_rows.append(row)
        elif kind == "flip":
            buf = bytearray(row)
            buf[rng.randrange(len(buf))] ^= 1 + rng.randrange(255)
            frame_rows.append(bytes(buf))
        elif kind == "truncate":
            frame_rows.append(row[:rng.randrange(len(row))])
        elif kind == "junk":
            frame_rows.append(rng.randbytes(rng.randrange(60)))
        else:
            frame_rows.append(b"")
    group = [(oracle.peer_id, scope, frame_rows)]
    status, _ = oracle.dispatch(
        P.OP_VOTE_BATCH, P.encode_vote_batch(_NOW + 1, group)
    )
    assert status == P.STATUS_OK
    # Second frame: the untouched remainder of the chain — exercises the
    # cross-frame dangling guard identically on both paths.
    rest = rows[len(plan):] or rows[:1]
    oracle.dispatch(
        P.OP_VOTE_BATCH,
        P.encode_vote_batch(_NOW + 1, [(oracle.peer_id, scope, rest)]),
    )


@settings(max_examples=120, deadline=None)
@given(
    base_rows=st.integers(min_value=0, max_value=3),
    cut=st.floats(min_value=0.0, max_value=1.0),
    extra=st.binary(max_size=12),
    bogus_count=st.integers(min_value=0, max_value=0xFFFFFFFF),
)
def test_vote_batch_frame_structure_fuzz(base_rows, cut, extra, bogus_count):
    """Frame-level fuzz: truncations, trailing garbage, and overflowing
    group counts report the SAME status (and message) on both paths —
    the columnar views decoder shares the object decoder's wire
    contract exactly."""
    oracle = _oracle()
    scope, rows = oracle.fresh_session()
    payload = P.encode_vote_batch(
        _NOW + 1, [(oracle.peer_id, scope, rows[:base_rows])]
    )
    truncated = payload[: int(len(payload) * cut)]
    oracle.dispatch(P.OP_VOTE_BATCH, truncated)
    oracle.dispatch(P.OP_VOTE_BATCH, payload + extra)
    # Length column that overflows the frame (claimed count with no
    # bytes behind it).
    oracle.dispatch(
        P.OP_VOTE_BATCH,
        P.u64(_NOW) + P.u32(1) + P.u32(oracle.peer_id) + P.string(scope)
        + P.u32(bogus_count),
    )
