"""Continuous profiling plane: always-on stack sampling, wall-clock
attribution reports, and the perf-regression sentry (round 20).

Covers the acceptance surface: per-role hot-function dominance in the
sampled aggregate, the adaptive-rate backoff/speed-up contract, the
collapsed-stack round trip, the bounded-aggregate drop accounting, the
``/profile`` sidecar endpoint and ``OP_PROFILE`` opcode serving the
same schema (with the old-peer UNKNOWN_OPCODE tolerance), BENCH_r19
device-apply-share reproduction from the checked-in artifact, the
noise-aware regression sentry over the real corpus (no false
regressions) and over synthetic corpora (a real drop IS flagged),
fleet federation via ``merge_profile_states``, ``IncidentCapture``'s
``profile.json``, and sim-corpus byte-identity with the sampler live.
"""

import json
import os
import pathlib
import threading
import time
import urllib.request

import pytest

from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.bridge.client import BridgeClient, BridgeError
from hashgraph_tpu.bridge.server import BridgeServer
from hashgraph_tpu.obs.attribution import (
    ATTRIBUTION_SCHEMA,
    STAGE_KEYS,
    attribution_report,
    report_from_stage_totals,
)
from hashgraph_tpu.obs.profiler import (
    PROFILE_SCHEMA,
    ContinuousProfiler,
    parse_collapsed,
    profiler_enabled,
    thread_role,
)

NOW = 1_700_000_000
REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


# ── role labelling ─────────────────────────────────────────────────────


class TestThreadRole:
    def test_prefix_table(self):
        assert thread_role("bridge-reader-3") == "reader"
        assert thread_role("bridge-shm-0") == "reader"
        assert thread_role("bridge-pipeline-1") == "serial-lane"
        assert thread_role("apply-reactor") == "reactor"
        assert thread_role("reactor-flusher") == "reactor"
        assert thread_role("gossip-loop-peer1") == "gossip-loop"
        assert thread_role("wal-writer") == "wal-fsync"
        assert thread_role("MainThread") == "main"
        assert thread_role("ThreadPoolExecutor-0_0") == "other"
        assert thread_role("") == "other"


# ── the sampling fold ──────────────────────────────────────────────────


def _hot_spin(stop: threading.Event) -> None:
    """A recognizable leaf frame for the dominance assertion."""
    while not stop.is_set():
        sum(range(64))


@pytest.fixture()
def hot_thread():
    """A running thread named like the serial-lane pool, pinned inside
    ``_hot_spin`` so every sample of it has a known hottest leaf."""
    stop = threading.Event()
    thread = threading.Thread(
        target=_hot_spin, args=(stop,), name="bridge-pipeline-0", daemon=True
    )
    thread.start()
    try:
        yield thread
    finally:
        stop.set()
        thread.join(timeout=5)


class TestSampling:
    def test_hot_function_dominates_its_role(self, hot_thread):
        prof = ContinuousProfiler()
        for _ in range(25):
            prof.sample_once()
        snap = prof.snapshot()
        assert snap["schema"] == PROFILE_SCHEMA
        assert snap["roles"].get("serial-lane", 0) >= 25
        lane = [s for s in snap["stacks"] if s["role"] == "serial-lane"]
        assert lane, "no serial-lane stacks sampled"
        # Hottest-first ordering + the pinned leaf: the spin function
        # must dominate the role's aggregate.
        hottest = lane[0]
        assert any("_hot_spin" in frame for frame in hottest["frames"])
        spin = sum(
            s["samples"]
            for s in lane
            if any("_hot_spin" in f for f in s["frames"])
        )
        total = sum(s["samples"] for s in lane)
        assert spin / total > 0.9

    def test_sampler_excludes_its_own_thread(self):
        prof = ContinuousProfiler(min_hz=50.0, max_hz=50.0)
        prof.start()
        try:
            time.sleep(0.3)
            snap = prof.snapshot()
            assert snap["samples"] > 0
            for entry in snap["stacks"]:
                assert not any(
                    "profiler._loop" in frame for frame in entry["frames"]
                )
        finally:
            prof.stop()

    def test_kill_switch_stops_sampling(self):
        prof = ContinuousProfiler(min_hz=50.0, max_hz=50.0)
        prof.enabled = False
        prof.start()
        try:
            time.sleep(0.25)
            assert prof.snapshot()["samples"] == 0
        finally:
            prof.stop()

    def test_bounded_aggregate_counts_drops(self, hot_thread):
        # Cap of 1 distinct stack: with >= 2 live threads (main + the
        # hot one) every tick lands at least one novel-stack drop after
        # the first key is admitted.
        prof = ContinuousProfiler(max_stacks=1)
        for _ in range(10):
            prof.sample_once()
        snap = prof.snapshot()
        assert len(snap["stacks"]) == 1
        assert snap["dropped"] > 0
        # Total accounting: admitted + dropped == every sample taken.
        admitted = sum(s["samples"] for s in snap["stacks"])
        assert admitted + snap["dropped"] == snap["samples"]

    def test_registry_counters_advance(self):
        from hashgraph_tpu.obs import MetricsRegistry
        from hashgraph_tpu.obs.profiler import (
            PROFILE_OVERHEAD_SECONDS_TOTAL,
            PROFILE_SAMPLES_TOTAL,
        )

        reg = MetricsRegistry()
        prof = ContinuousProfiler(reg)
        prof.sample_once()
        prof._adapt(0.001)
        snap = reg.snapshot()
        assert snap["counters"][PROFILE_SAMPLES_TOTAL] > 0
        assert snap["counters"][PROFILE_OVERHEAD_SECONDS_TOTAL] > 0


class TestAdaptiveRate:
    def test_backoff_to_floor_when_over_budget(self):
        prof = ContinuousProfiler(min_hz=19.0, max_hz=97.0)
        start_hz = prof.rate_hz
        # Every tick costs more than the whole interval: the EWMA blows
        # through the budget and the rate must walk down to the floor.
        for _ in range(50):
            prof._adapt(2.0 / prof.rate_hz)
        assert prof.rate_hz < start_hz
        assert prof.rate_hz == pytest.approx(19.0)

    def test_speedup_to_ceiling_when_cheap(self):
        prof = ContinuousProfiler(min_hz=19.0, max_hz=97.0)
        for _ in range(50):
            prof._adapt(2.0 / prof.rate_hz)  # drive to the floor first
        for _ in range(80):
            prof._adapt(0.0)  # free ticks: well under half the budget
        assert prof.rate_hz == pytest.approx(97.0)

    def test_rate_never_leaves_the_band(self):
        prof = ContinuousProfiler(min_hz=19.0, max_hz=97.0)
        for k in range(200):
            prof._adapt(0.0 if k % 3 else 1.0)
            assert 19.0 <= prof.rate_hz <= 97.0 + 1e-9


class TestCollapsedRoundTrip:
    def test_collapsed_parses_back_exactly(self, hot_thread):
        prof = ContinuousProfiler()
        for _ in range(10):
            prof.sample_once()
        snap = prof.snapshot()
        parsed = parse_collapsed(prof.collapsed(snap))
        expect = {
            (s["role"], tuple(s["frames"])): s["samples"]
            for s in snap["stacks"]
        }
        assert parsed == expect
        assert any(role == "serial-lane" for role, _frames in parsed)

    def test_empty_profile_collapses_to_empty_text(self):
        prof = ContinuousProfiler()
        assert prof.collapsed() == ""
        assert parse_collapsed("") == {}


class TestChromeExport:
    def test_samples_ride_pid_zero_with_role_threads(self, hot_thread):
        prof = ContinuousProfiler()
        for _ in range(5):
            prof.sample_once()
        doc = prof.export_chrome()
        events = doc["traceEvents"]
        # export_chrome merges the shared trace ring, so other suites'
        # consensus instants may ride along on their own pids — the
        # pid-0 contract covers the profiler's sample instants only.
        instants = [
            e
            for e in events
            if e.get("ph") == "i" and "role" in e.get("args", {})
        ]
        assert instants and all(e["pid"] == 0 for e in instants)
        names = [
            e["args"]["name"]
            for e in events
            if e.get("ph") == "M" and e["name"] == "thread_name"
        ]
        assert any("serial-lane" in n for n in names)
        assert doc["otherData"]["profile"]["samples"] == prof.snapshot()[
            "samples"
        ]

    def test_export_writes_loadable_json(self, tmp_path, hot_thread):
        prof = ContinuousProfiler()
        prof.sample_once()
        path = tmp_path / "trace.json"
        prof.export_chrome(str(path))
        doc = json.loads(path.read_text())
        assert "traceEvents" in doc


class TestEnvGate:
    def test_profiler_enabled_contract(self, monkeypatch):
        monkeypatch.delenv("HASHGRAPH_TPU_PROFILE", raising=False)
        assert profiler_enabled(None) is False  # default OFF
        assert profiler_enabled(True) is True
        monkeypatch.setenv("HASHGRAPH_TPU_PROFILE", "1")
        assert profiler_enabled(None) is True
        assert profiler_enabled(False) is False  # explicit wins

    def test_server_start_arms_default_profiler(self, monkeypatch):
        from hashgraph_tpu.obs import default_profiler

        monkeypatch.setenv("HASHGRAPH_TPU_PROFILE", "1")
        assert not default_profiler.running
        try:
            with BridgeServer(capacity=8, voter_capacity=4):
                assert default_profiler.running
        finally:
            default_profiler.stop()
            default_profiler.reset()

    def test_server_start_respects_default_off(self, monkeypatch):
        from hashgraph_tpu.obs import default_profiler

        monkeypatch.delenv("HASHGRAPH_TPU_PROFILE", raising=False)
        with BridgeServer(capacity=8, voter_capacity=4):
            assert not default_profiler.running


# ── the attribution report and its three surfaces ──────────────────────


class TestAttributionReport:
    def test_shares_sum_to_one_over_busy_time(self):
        report = report_from_stage_totals(
            {
                "wire_decode_s": 1.0,
                "crypto_s": 2.0,
                "device_apply_s": 5.0,
                "wal_fsync_s": 2.0,
                "device_dispatches": 10.0,
                "apply_rows": 320.0,
            }
        )
        assert report["schema"] == ATTRIBUTION_SCHEMA
        assert set(report["stages"]) == set(STAGE_KEYS)
        assert sum(s["share"] for s in report["stages"].values()) == (
            pytest.approx(1.0, abs=1e-3)
        )
        assert report["stages"]["device_apply"]["share"] == 0.5
        assert report["device"]["votes_per_dispatch"] == 32.0

    def test_empty_totals_do_not_divide_by_zero(self):
        report = report_from_stage_totals({})
        assert report["busy_seconds"] == 0.0
        assert all(s["share"] == 0.0 for s in report["stages"].values())

    def test_bench_r19_device_apply_share_reproduced(self):
        """Acceptance: the report reproduces the checked-in round-19
        device-apply shares (off 0.588 / on 0.509) and amortization
        factors EXACTLY — same formula, same inputs, no coincidence."""
        body = json.load(open(REPO_ROOT / "BENCH_r19.json"))
        block = body["detail"]["reactor_ab"]
        for arm in ("off", "on"):
            report = report_from_stage_totals(block["stage_totals"][arm])
            assert report["stages"]["device_apply"]["share"] == (
                pytest.approx(block["device_apply_share"][arm], abs=1e-3)
            ), arm
            assert report["device"]["votes_per_dispatch"] == (
                pytest.approx(block["votes_per_dispatch"][arm], abs=0.01)
            ), arm

    def test_live_report_fuses_profiler_samples(self, hot_thread):
        prof = ContinuousProfiler()
        for _ in range(5):
            prof.sample_once()
        report = attribution_report(
            state={"counters": {}, "histograms": {}}, profiler=prof
        )
        assert report["samples"]["total"] == prof.snapshot()["samples"]
        assert "serial-lane" in report["samples"]["roles"]

    def test_idle_profiler_contributes_no_samples_block(self):
        report = attribution_report(
            state={"counters": {}, "histograms": {}},
            profiler=ContinuousProfiler(),
        )
        assert "samples" not in report


class TestProfileSurfaces:
    def test_sidecar_and_opcode_serve_the_same_schema(self):
        from hashgraph_tpu.obs import registry

        # Both surfaces read the LIVE process registry: advance a stage
        # counter and the pulled reports must see a non-zero busy time.
        registry.counter(
            "hashgraph_bridge_wire_apply_seconds_total"
        ).inc(0.25)
        with BridgeServer(
            capacity=16, voter_capacity=8, metrics_port=0
        ) as server:
            host, port = server.metrics_address
            with BridgeClient(*server.address) as client:
                alice, _ = client.add_peer()
                bob, _ = client.add_peer()
                pid, _ = client.create_proposal(
                    alice, "prof", NOW, "p", b"", 4, 600
                )
                proposal = client.get_proposal(alice, "prof", pid)
                client.process_proposal(bob, "prof", proposal, NOW + 1)
                vote = client.cast_vote(bob, "prof", pid, True, NOW + 2)
                client.process_votes(alice, "prof", [vote], NOW + 3)
                with urllib.request.urlopen(
                    f"http://{host}:{port}/profile", timeout=5
                ) as response:
                    http_body = json.loads(response.read())
                frame = client.profile()
        assert http_body["schema"] == ATTRIBUTION_SCHEMA
        assert set(http_body["stages"]) == set(STAGE_KEYS)
        assert http_body["busy_seconds"] > 0  # the vote above was applied
        assert frame is not None
        assert frame["profile"]["schema"] == ATTRIBUTION_SCHEMA
        assert set(frame["profile"]["stages"]) == set(STAGE_KEYS)
        assert frame["host"], "OP_PROFILE frame must carry the host label"

    def test_old_peer_unknown_opcode_returns_none(self, monkeypatch):
        with BridgeServer(capacity=8, voter_capacity=4) as server:
            with BridgeClient(*server.address) as client:
                def refuse(opcode, payload=b"", *a, **kw):
                    raise BridgeError(
                        P.STATUS_UNKNOWN_OPCODE, "old peer"
                    )

                monkeypatch.setattr(client, "_call", refuse)
                assert client.profile() is None

    def test_other_bridge_errors_still_raise(self, monkeypatch):
        with BridgeServer(capacity=8, voter_capacity=4) as server:
            with BridgeClient(*server.address) as client:
                def explode(opcode, payload=b"", *a, **kw):
                    raise BridgeError(P.STATUS_INTERNAL, "boom")

                monkeypatch.setattr(client, "_call", explode)
                with pytest.raises(BridgeError):
                    client.profile()

    def test_incident_capture_writes_profile_json(self, tmp_path):
        from hashgraph_tpu.obs.slo import IncidentCapture

        cap = IncidentCapture(str(tmp_path))
        path = cap.capture("slo_breach", scope="s")
        assert path is not None
        body = json.load(open(os.path.join(path, "profile.json")))
        assert body["schema"] == ATTRIBUTION_SCHEMA
        assert set(body["stages"]) == set(STAGE_KEYS)


class TestFleetMerge:
    def _frame(self, host, decode, crypto, apply_s, samples):
        return {
            "host": host,
            "profile": {
                "schema": ATTRIBUTION_SCHEMA,
                "stages": {
                    "wire_decode": {"seconds": decode, "share": 0.0},
                    "crypto": {"seconds": crypto, "share": 0.0},
                    "device_apply": {"seconds": apply_s, "share": 0.0},
                    "wal_fsync": {"seconds": 0.0, "share": 0.0},
                },
                "device": {"dispatches": 4.0, "apply_rows": 64.0},
                "wal": {"fsyncs": 2},
                "samples": {
                    "total": samples,
                    "dropped": 1,
                    "overhead_seconds": 0.01,
                    "roles": {"reader": samples},
                },
            },
        }

    def test_shares_recomputed_over_fleet_denominator(self):
        from hashgraph_tpu.parallel.rollup import merge_profile_states

        merged = merge_profile_states(
            [
                self._frame("h1", 1.0, 1.0, 6.0, 10),
                self._frame("h2", 1.0, 1.0, 2.0, 30),
            ]
        )
        assert set(merged["hosts"]) == {"h1", "h2"}
        assert merged["busy_seconds"] == pytest.approx(12.0)
        # 8/12 device-apply fleet-wide — NOT the mean of per-host shares.
        assert merged["stages"]["device_apply"]["share"] == (
            pytest.approx(8.0 / 12.0, abs=1e-3)
        )
        assert merged["device"]["votes_per_dispatch"] == 16.0
        assert merged["wal"]["fsyncs"] == 4
        assert merged["samples"]["total"] == 40
        assert merged["samples"]["roles"] == {"reader": 40}

    def test_empty_and_degenerate_frames_merge_clean(self):
        from hashgraph_tpu.parallel.rollup import merge_profile_states

        merged = merge_profile_states([{"host": "h1"}, {}])
        assert merged["busy_seconds"] == 0.0
        assert all(
            s["share"] == 0.0 for s in merged["stages"].values()
        )


# ── determinism: the sampler must be protocol-invisible ────────────────


class TestDeterminism:
    def test_sim_verdict_byte_identical_with_profiler_on(self):
        """Acceptance: the chaos harness's verdict JSON is byte-for-byte
        identical with the always-on sampler live — sampling reads
        interpreter frames, never protocol state."""
        from hashgraph_tpu.sim.scenarios import run_scenario

        baseline = run_scenario("partition-heal", 7)
        prof = ContinuousProfiler(min_hz=50.0, max_hz=97.0)
        prof.start()
        try:
            sampled = run_scenario("partition-heal", 7)
        finally:
            prof.stop()
        assert prof.snapshot()["samples"] > 0, "sampler never fired"
        assert json.dumps(baseline, sort_keys=True) == json.dumps(
            sampled, sort_keys=True
        )


# ── the perf-regression sentry ─────────────────────────────────────────


class TestBenchRegress:
    def test_real_corpus_no_false_regressions(self):
        """Acceptance: the checked-in trajectory must come out clean —
        every genuine drop in it (r01 TPU -> r05 CPU) is advisory
        because the artifacts cannot support a confident claim."""
        from tools.bench_regress import build_verdict

        verdict = build_verdict(REPO_ROOT)
        assert verdict["pass"] is True
        assert verdict["regressions"] == []
        assert verdict["entries"] >= 7
        skipped = {s["file"] for s in verdict["skipped"]}
        assert skipped == {
            "BENCH_r02.json", "BENCH_r03.json", "BENCH_r04.json"
        }
        shares = verdict["stage_shares"]["device_apply"]
        assert [s["share"] for s in shares] == [0.668, 0.588, 0.509]

    @staticmethod
    def _artifact(path, round_no, value, spread):
        path.write_text(json.dumps({
            "metric": "vote_ingest_throughput",
            "value": value,
            "unit": "votes/sec",
            "detail": {"headline_spread_pct": spread},
            "round": round_no,
        }))

    def test_synthetic_regression_is_flagged(self, tmp_path):
        from tools.bench_regress import build_verdict

        self._artifact(tmp_path / "BENCH_r21.json", 21, 1000.0, 2.0)
        self._artifact(tmp_path / "BENCH_r22.json", 22, 500.0, 2.0)
        verdict = build_verdict(tmp_path)
        assert verdict["pass"] is False
        assert len(verdict["regressions"]) == 1
        reg = verdict["regressions"][0]
        assert reg["delta_pct"] == pytest.approx(-50.0)
        assert reg["verdict"] == "regression"

    def test_drop_within_recorded_spread_is_stable(self, tmp_path):
        from tools.bench_regress import build_verdict

        self._artifact(tmp_path / "BENCH_r21.json", 21, 1000.0, 10.0)
        self._artifact(tmp_path / "BENCH_r22.json", 22, 900.0, 10.0)
        verdict = build_verdict(tmp_path)
        assert verdict["pass"] is True
        assert verdict["regressions"] == []

    def test_spreadless_round_cannot_convict(self, tmp_path):
        from tools.bench_regress import build_verdict

        self._artifact(tmp_path / "BENCH_r21.json", 21, 1000.0, None)
        self._artifact(tmp_path / "BENCH_r22.json", 22, 100.0, 2.0)
        verdict = build_verdict(tmp_path)
        assert verdict["pass"] is True  # advisory, not a conviction
        comparisons = verdict["series"][
            "vote_ingest_throughput"
        ]["comparisons"]
        assert comparisons[0]["verdict"] == "advisory"
