"""The north-star integration: an unchanged ConsensusService running on
TpuBackedStorage — session state resident in the device pool, identical
observable behavior, device replica tracking every transition."""

import pytest

from hashgraph_tpu import (
    BroadcastEventBus,
    ConsensusReached,
    ConsensusService,
    CreateProposalRequest,
    InsufficientVotesAtTimeout,
    NetworkType,
    build_vote,
)
from hashgraph_tpu.engine import TpuBackedStorage
from hashgraph_tpu.ops import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
)

from common import NOW, random_stub_signer


def make_tpu_service():
    storage = TpuBackedStorage(capacity=32, voter_capacity=8)
    service = ConsensusService(storage, BroadcastEventBus(), random_stub_signer())
    return service, storage


def request(n=3, exp=100, liveness=True, name="p"):
    return CreateProposalRequest(
        name=name,
        payload=b"",
        proposal_owner=b"o",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


class TestServiceOnTpuStorage:
    def test_quickstart_flow_with_device_tracking(self):
        service, storage = make_tpu_service()
        receiver = service.event_bus().subscribe()
        pid = service.create_proposal("s", request(3), NOW).proposal_id
        assert storage.device_state_of("s", pid) == STATE_ACTIVE

        service.cast_vote("s", pid, True, NOW)
        assert storage.device_state_of("s", pid) == STATE_ACTIVE

        vote = build_vote(
            storage.get_proposal("s", pid), True, random_stub_signer(), NOW
        )
        service.process_incoming_vote("s", vote, NOW)

        # Scalar truth and device replica agree.
        assert storage.get_consensus_result("s", pid) is True
        assert storage.device_state_of("s", pid) == STATE_REACHED_YES
        scope, event = receiver.recv(timeout=1)
        assert event == ConsensusReached(pid, True, NOW)

    def test_timeout_paths_track_on_device(self):
        service, storage = make_tpu_service()
        # liveness YES fill -> decided at timeout.
        pid_yes = service.create_proposal("s", request(5, liveness=True), NOW).proposal_id
        service.cast_vote("s", pid_yes, True, NOW)
        assert service.handle_consensus_timeout("s", pid_yes, NOW + 200) is True
        assert storage.device_state_of("s", pid_yes) == STATE_REACHED_YES

        # Tie at threshold 1.0 -> Failed.
        service.scope("t").with_threshold(1.0).initialize()
        pid_fail = service.create_proposal("t", request(4, liveness=True), NOW).proposal_id
        for i, signer in enumerate([random_stub_signer(), random_stub_signer()]):
            vote = build_vote(
                storage.get_proposal("t", pid_fail), i % 2 == 0, signer, NOW
            )
            service.process_incoming_vote("t", vote, NOW)
        with pytest.raises(InsufficientVotesAtTimeout):
            service.handle_consensus_timeout("t", pid_fail, NOW + 200)
        assert storage.device_state_of("t", pid_fail) == STATE_FAILED

    def test_p2p_round_cap_tracks_failed(self):
        service, storage = make_tpu_service()
        service.scope("s").with_network_type(NetworkType.P2P).initialize()
        # liveness=False and a Y,N,Y spread keep the session undecided
        # through the cap: yes_w=2 < req=3, no_w=1+1 silent=2, no tie.
        pid = service.create_proposal(
            "s", request(4, liveness=False), NOW
        ).proposal_id
        # P2P cap = ceil(2*4/3) = 3 votes; the 4th errors and fails the session.
        from hashgraph_tpu import MaxRoundsExceeded

        voters = [random_stub_signer() for _ in range(4)]
        for voter, choice in zip(voters[:3], [True, False, True]):
            vote = build_vote(storage.get_proposal("s", pid), choice, voter, NOW)
            service.process_incoming_vote("s", vote, NOW)
        vote = build_vote(storage.get_proposal("s", pid), True, voters[3], NOW)
        with pytest.raises(MaxRoundsExceeded):
            service.process_incoming_vote("s", vote, NOW)
        assert storage.device_state_of("s", pid) == STATE_FAILED

    def test_eviction_releases_pool_slots(self):
        storage = TpuBackedStorage(capacity=8, voter_capacity=8)
        service = ConsensusService(
            storage, BroadcastEventBus(), random_stub_signer(),
            max_sessions_per_scope=2,
        )
        for i in range(5):
            service.create_proposal("s", request(3, name=f"p{i}"), NOW + i)
        assert len(storage.list_scope_sessions("s")) == 2
        assert storage.pool().allocated_slots == 2

    def test_shared_pool_with_engine_view(self):
        """Storage and batch engine can share one device pool."""
        from hashgraph_tpu.engine import ProposalPool

        pool = ProposalPool(16, 8)
        storage = TpuBackedStorage(pool=pool)
        service = ConsensusService(storage, BroadcastEventBus(), random_stub_signer())
        pid = service.create_proposal("s", request(3), NOW).proposal_id
        assert pool.allocated_slots == 1
        service.cast_vote("s", pid, True, NOW)
        assert storage.device_state_of("s", pid) == STATE_ACTIVE
