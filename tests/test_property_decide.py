"""Property-based fuzz: the device decision kernel vs the scalar oracle.

The decision math is the bit-exactness heart of the framework (SURVEY §2.1:
n≤2 unanimity, quorum gate, silent weighting, strict majority, tie-break,
and the f64-epsilon 2/3 special case). Hypothesis explores the input space
far beyond the transcribed reference tables.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from hashgraph_tpu.protocol import (
    calculate_threshold_based_value,
    decide as scalar_decide,
)
from hashgraph_tpu.ops.decide import decide_kernel, required_votes_np

thresholds = st.one_of(
    st.just(2.0 / 3.0),
    st.floats(min_value=0.0, max_value=1.0, allow_nan=False),
    # Values epsilon-close to 2/3 probe the div_ceil special case boundary.
    st.builds(
        lambda ulps: float(np.nextafter(2.0 / 3.0, 1.0 if ulps > 0 else 0.0))
        if ulps
        else 2.0 / 3.0,
        st.integers(min_value=-1, max_value=1),
    ),
)


@settings(max_examples=300, deadline=None)
@given(
    n=st.integers(min_value=1, max_value=2000),
    threshold=thresholds,
)
def test_required_votes_matches_scalar(n, threshold):
    scalar = calculate_threshold_based_value(n, threshold)
    vectorized = int(required_votes_np(np.array([n]), threshold)[0])
    assert scalar == vectorized


@settings(max_examples=500, deadline=None)
@given(
    n=st.integers(min_value=1, max_value=300),
    data=st.data(),
    threshold=thresholds,
    liveness=st.booleans(),
    is_timeout=st.booleans(),
)
def test_decide_kernel_matches_scalar(n, data, threshold, liveness, is_timeout):
    total = data.draw(st.integers(min_value=0, max_value=n + 5))
    yes = data.draw(st.integers(min_value=0, max_value=total))

    expected = scalar_decide(yes, total, n, threshold, liveness, is_timeout)

    req = required_votes_np(np.array([n]), threshold)
    decided, result = decide_kernel(
        jnp.array([yes], jnp.int32),
        jnp.array([total], jnp.int32),
        jnp.array([n], jnp.int32),
        jnp.asarray(req, jnp.int32),
        jnp.array([liveness]),
        jnp.array([is_timeout]),
    )
    got = bool(result[0]) if bool(decided[0]) else None
    assert got == expected, (
        f"n={n} yes={yes} total={total} threshold={threshold!r} "
        f"liveness={liveness} timeout={is_timeout}: scalar={expected} device={got}"
    )
