"""Embedder bridge: the README 3-voter quick-start from outside Python.

The reference is embedded in-process from Rust (reference: README.md:41-82,
183-197); this framework's equivalent embedder boundary is the framed TCP
protocol in hashgraph_tpu/bridge. Covered here:

- the full quick-start through the Python reference client,
- the same scenario through the compiled C client (native/bridge_client.c),
  proving a non-Python process can create proposals, vote, ferry wire bytes
  and receive events,
- error-path parity: wire statuses mirror StatusCode, bridge-level statuses
  cover unknown peers/opcodes, tampered votes are rejected with the same
  error the in-process engine raises.
"""

import shutil
import socket
import struct
import subprocess
import sys

import pytest

from hashgraph_tpu.bridge import BridgeClient, BridgeError, BridgeServer
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.errors import ConsensusFailed, StatusCode
from hashgraph_tpu.wire import Vote

NOW = 1_700_000_000


@pytest.fixture(scope="module")
def server():
    with BridgeServer(capacity=64, voter_capacity=8) as srv:
        yield srv


@pytest.fixture()
def client(server):
    with BridgeClient(*server.address) as cl:
        yield cl


def run_quickstart(cl: BridgeClient, scope: str):
    """3 voters, gossipsub defaults, unanimous YES; returns (peers, pid)."""
    peers = [cl.add_peer()[0] for _ in range(3)]
    pid, _ = cl.create_proposal(peers[0], scope, NOW, "upgrade", b"ship", 3, 600)
    cl.cast_vote(peers[0], scope, pid, True, NOW + 1)
    proposal = cl.get_proposal(peers[0], scope, pid)
    for peer in peers[1:]:
        cl.process_proposal(peer, scope, proposal, NOW + 2)
    for i, voter in enumerate(peers[1:], start=1):
        vote = cl.cast_vote(voter, scope, pid, True, NOW + 2 + i)
        for other in peers:
            if other != voter:
                cl.process_vote(other, scope, vote, NOW + 3 + i)
    return peers, pid


class TestPythonClient:
    def test_quickstart_reaches_consensus_on_all_peers(self, client):
        peers, pid = run_quickstart(client, "qs")
        for peer in peers:
            assert client.get_result(peer, "qs", pid) is True
            events = client.poll_events(peer)
            assert any(
                e.kind == P.EVENT_REACHED and e.proposal_id == pid and e.result
                for e in events
            )

    def test_stats_and_identities(self, client):
        peer, identity = client.add_peer()
        assert len(identity) == 20  # Ethereum address
        pid, _ = client.create_proposal(peer, "st", NOW, "p", b"", 3, 600)
        assert client.get_stats(peer, "st") == (1, 1, 0, 0)
        assert client.get_result(peer, "st", pid) is None

    def test_explicit_key_yields_deterministic_identity(self, client):
        key = (7).to_bytes(32, "big")
        _, identity = client.add_peer(key)
        from hashgraph_tpu.signing.ethereum import EthereumConsensusSigner

        assert identity == EthereumConsensusSigner(key).identity()

    def test_duplicate_vote_maps_to_wire_status(self, client):
        peer, _ = client.add_peer()
        pid, _ = client.create_proposal(peer, "dup", NOW, "p", b"", 3, 600)
        client.cast_vote(peer, "dup", pid, True, NOW + 1)
        with pytest.raises(BridgeError) as exc:
            client.cast_vote(peer, "dup", pid, True, NOW + 2)
        assert exc.value.status == int(StatusCode.USER_ALREADY_VOTED)

    def test_timeout_without_quorum_fails_session(self, client):
        # n=2 runs the unanimity rule (reference: src/utils.rs:239-244):
        # zero votes at timeout is undecidable regardless of liveness, so the
        # session fails and the wire carries INSUFFICIENT_VOTES_AT_TIMEOUT.
        peer, _ = client.add_peer()
        pid, _ = client.create_proposal(peer, "to", NOW, "p", b"", 2, 600)
        with pytest.raises(BridgeError) as exc:
            client.handle_timeout(peer, "to", pid, NOW + 700)
        assert exc.value.status == int(StatusCode.INSUFFICIENT_VOTES_AT_TIMEOUT)
        with pytest.raises(ConsensusFailed):
            client.get_result(peer, "to", pid)
        events = client.poll_events(peer)
        assert any(e.kind == P.EVENT_FAILED and e.proposal_id == pid for e in events)

    def test_tampered_vote_rejected_like_in_process(self, client):
        alice, _ = client.add_peer()
        bob, _ = client.add_peer()
        pid, _ = client.create_proposal(alice, "tam", NOW, "p", b"", 3, 600)
        proposal = client.get_proposal(alice, "tam", pid)
        client.process_proposal(bob, "tam", proposal, NOW + 1)
        vote_bytes = client.cast_vote(bob, "tam", pid, False, NOW + 2)
        vote = Vote.decode(vote_bytes)
        vote.vote = True  # flip the choice without re-signing
        with pytest.raises(BridgeError) as exc:
            client.process_vote(alice, "tam", vote.encode(), NOW + 3)
        assert exc.value.status == int(StatusCode.INVALID_VOTE_HASH)

    def test_batch_vote_delivery(self, client):
        """OP_PROCESS_VOTES: one frame carries the whole vote batch; the
        per-vote status list mirrors in-process ingest_votes (mixed
        accept / duplicate / unknown-session codes in batch order)."""
        alice, _ = client.add_peer()
        bob, _ = client.add_peer()
        pid, _ = client.create_proposal(alice, "bat", NOW, "p", b"", 4, 600)
        proposal = client.get_proposal(alice, "bat", pid)
        client.process_proposal(bob, "bat", proposal, NOW + 1)
        v_bob = client.cast_vote(bob, "bat", pid, True, NOW + 2)
        unknown = Vote.decode(v_bob)
        unknown.proposal_id = 999_999_999
        statuses = client.process_votes(
            alice,
            "bat",
            [v_bob, v_bob, unknown.encode(), b"\xff\xff garbage"],
            NOW + 3,
        )
        assert statuses == [
            int(StatusCode.OK),
            int(StatusCode.DUPLICATE_VOTE),
            int(StatusCode.SESSION_NOT_FOUND),
            P.STATUS_BAD_REQUEST,  # undecodable blob: per-vote, not fatal
        ]

    def test_unknown_peer_and_session(self, client):
        with pytest.raises(BridgeError) as exc:
            client.get_result(999_999, "x", 1)
        assert exc.value.status == P.STATUS_UNKNOWN_PEER
        peer, _ = client.add_peer()
        with pytest.raises(BridgeError) as exc:
            client.get_result(peer, "x", 12345)
        assert exc.value.status == int(StatusCode.SESSION_NOT_FOUND)

    def test_unknown_opcode_and_truncated_frame(self, server):
        host, port = server.address
        with socket.create_connection((host, port), timeout=10) as sock:
            sock.sendall(P.encode_frame(137, b""))
            status, _ = P.read_frame(sock)
            assert status == P.STATUS_UNKNOWN_OPCODE
        with socket.create_connection((host, port), timeout=10) as sock:
            # CREATE_PROPOSAL with a truncated payload: bad request, then the
            # server keeps serving new connections.
            sock.sendall(P.encode_frame(P.OP_CREATE_PROPOSAL, struct.pack("<I", 1)))
            status, _ = P.read_frame(sock)
            assert status == P.STATUS_BAD_REQUEST
        with BridgeClient(host, port) as cl:
            assert cl.ping() == P.PROTOCOL_VERSION


class TestTraceContextOnTheWire:
    def test_create_response_carries_bound_context(self, client):
        pid, _ = client.create_proposal(client.add_peer()[0], "tr1", NOW, "p", b"", 3, 600)
        ctx = client.last_trace_context
        assert ctx is not None
        assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 8

    def test_context_propagates_across_peers(self, client):
        alice, _ = client.add_peer()
        bob, _ = client.add_peer()
        pid, proposal = client.create_proposal(alice, "tr2", NOW, "p", b"", 3, 600)
        ctx = client.last_trace_context
        client.process_proposal(bob, "tr2", proposal, NOW + 1, trace=ctx)
        vote = client.cast_vote(bob, "tr2", pid, True, NOW + 2)
        bob_ctx = client.last_trace_context
        # Same trace on both peers, different span identities.
        assert bob_ctx.trace_id == ctx.trace_id
        assert bob_ctx.span_id != ctx.span_id
        client.process_vote(alice, "tr2", vote, NOW + 3, trace=ctx)

    def test_old_wire_client_interoperates(self, server):
        """A seed-protocol embedder: frames WITHOUT trace suffixes, and
        response tails ignored. Must decode identically and decide."""
        host, port = server.address
        with socket.create_connection((host, port), timeout=10) as sock:
            def call(opcode, payload):
                sock.sendall(P.encode_frame(opcode, payload))
                status, cursor = P.read_frame(sock)
                assert status == P.STATUS_OK, status
                return cursor

            peer = call(P.OP_ADD_PEER, P.u8(0)).u32()
            # CREATE_PROPOSAL exactly as the seed client encoded it.
            cursor = call(
                P.OP_CREATE_PROPOSAL,
                P.u32(peer) + P.string("old") + P.u64(NOW) + P.string("p")
                + P.blob(b"") + P.u32(1) + P.u64(600) + P.u8(1),
            )
            pid = cursor.u32()
            cursor.blob()
            assert not cursor.done()  # new server appended a suffix...
            # ...which an old client simply never reads. Keep going:
            call(
                P.OP_CAST_VOTE,
                P.u32(peer) + P.string("old") + P.u32(pid) + P.u8(1) + P.u64(NOW + 1),
            )
            result = call(
                P.OP_GET_RESULT, P.u32(peer) + P.string("old") + P.u32(pid)
            ).u8()
            assert result == P.RESULT_YES

    def test_short_or_unknown_suffix_tails_are_tolerated(self, server):
        """Trailing bytes that are not a well-formed version-0 suffix —
        short fragments, future versions — are consumed and ignored, the
        same tolerance the pre-suffix server gave all trailing bytes."""
        host, port = server.address
        with socket.create_connection((host, port), timeout=10) as sock:
            def call(opcode, payload):
                sock.sendall(P.encode_frame(opcode, payload))
                status, cursor = P.read_frame(sock)
                return status, cursor

            status, cursor = call(P.OP_ADD_PEER, P.u8(0))
            assert status == P.STATUS_OK
            peer = cursor.u32()
            base = (
                P.u32(peer) + P.string("tail") + P.u64(NOW) + P.string("p")
                + P.blob(b"") + P.u32(3) + P.u64(600) + P.u8(1)
            )
            for tail in (b"\x07\x07\x07", P.u8(9) + b"z" * 25):
                status, _ = call(P.OP_CREATE_PROPOSAL, base + tail)
                assert status == P.STATUS_OK, (tail, status)

    def test_suffixed_and_bare_frames_decode_identically(self, client):
        """The same PROCESS_PROPOSAL bytes land the same session state
        whether or not the optional suffix is present."""
        alice, _ = client.add_peer()
        peers = [client.add_peer()[0] for _ in range(2)]
        pid, proposal = client.create_proposal(alice, "tr3", NOW, "p", b"", 3, 600)
        ctx = client.last_trace_context
        client.process_proposal(peers[0], "tr3", proposal, NOW + 1, trace=ctx)
        client.process_proposal(peers[1], "tr3", proposal, NOW + 1)  # bare
        assert client.get_stats(peers[0], "tr3") == client.get_stats(peers[1], "tr3")


class TestExplainOpcode:
    def test_explain_decided_proposal(self, client):
        peers, pid = run_quickstart(client, "expl")
        verdict = client.explain(peers[0], "expl", pid)
        assert verdict["status"] == "reached" and verdict["result"] is True
        quorum = verdict["quorum"]
        assert quorum["expected_voters"] == 3
        assert quorum["required_votes"] == 2  # div_ceil(2*3, 3)
        assert quorum["rule"] == "div_ceil(2n, 3)"
        # Quorum hits at 2 of 3 — the last vote arrives post-decision
        # (ALREADY_REACHED) and is not part of the accepted chain.
        assert quorum["yes"] >= quorum["required_votes"] and quorum["reached"]
        assert quorum["recomputed_result"] is True
        assert len(verdict["vote_chain"]) == quorum["total"]
        assert len(verdict["contributions"]) == quorum["total"]
        assert verdict["timeline"]["outcome"] == "yes"
        assert verdict["trace"] is not None

    def test_explain_unknown_session_maps_status(self, client):
        peer, _ = client.add_peer()
        with pytest.raises(BridgeError) as exc:
            client.explain(peer, "expl", 987654)
        assert exc.value.status == int(StatusCode.SESSION_NOT_FOUND)


class TestConcurrentClients:
    def test_parallel_connections_share_peers_safely(self, server):
        """Many connections driving the same peer concurrently: the engine's
        lock must serialize mutations so exactly the expected vote set lands
        (reference concurrency contract, tests/concurrency_tests.rs)."""
        import threading

        host, port = server.address
        with BridgeClient(host, port) as setup:
            alice, _ = setup.add_peer()
            pid, _ = setup.create_proposal(alice, "cc", NOW, "p", b"", 32, 600)
            proposal = setup.get_proposal(alice, "cc", pid)
            # 8 remote voters, one engine-backed peer each, pre-built votes.
            voters = [setup.add_peer()[0] for _ in range(8)]
            votes = []
            for voter in voters:
                setup.process_proposal(voter, "cc", proposal, NOW + 1)
                votes.append(setup.cast_vote(voter, "cc", pid, True, NOW + 2))

        statuses: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def deliver(i: int, vote: bytes) -> None:
            try:
                with BridgeClient(host, port) as cl:
                    # Each thread its own connection; two deliveries per
                    # vote so duplicates race against first-writers.
                    statuses[i] = cl.process_votes(
                        alice, "cc", [vote, vote], NOW + 3
                    )
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(target=deliver, args=(i, v))
            for i, v in enumerate(votes)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
        flat = [s for pair in statuses.values() for s in pair]
        # Exactly one success per voter; the duplicate copy is rejected
        # (or arrives after decision as ALREADY_REACHED).
        ok = flat.count(int(StatusCode.OK)) + flat.count(28)
        dup = flat.count(int(StatusCode.DUPLICATE_VOTE))
        assert ok == 8 and dup == 8, flat
        with BridgeClient(host, port) as check:
            assert check.get_stats(alice, "cc") == (1, 1, 0, 0)


class TestBridgeOverShardedEngine:
    def test_quickstart_on_device_mesh_engine(self):
        """engine_factory wires the bridge to a sharded device-mesh engine:
        a TCP embedder drives peers whose consensus state is sharded over
        the full (virtual) device mesh — bridge and parallel substrate
        composed end-to-end."""
        from hashgraph_tpu.engine import TpuConsensusEngine
        from hashgraph_tpu.parallel import ShardedPool, consensus_mesh

        mesh = consensus_mesh()

        def factory(signer):
            return TpuConsensusEngine(
                signer,
                pool=ShardedPool(capacity_per_device=4, voter_capacity=8, mesh=mesh),
            )

        with BridgeServer(engine_factory=factory) as server:
            with BridgeClient(*server.address) as client:
                peers, pid = run_quickstart(client, "mesh")
                for peer in peers:
                    assert client.get_result(peer, "mesh", pid) is True
                    events = client.poll_events(peer)
                    assert any(
                        e.kind == P.EVENT_REACHED and e.result for e in events
                    )


class TestCClient:
    def test_c_quickstart_end_to_end(self, server, tmp_path):
        """Compile the C embedder and let it run the whole scenario."""
        cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
        if cc is None:
            pytest.skip("no C compiler available")
        binary = tmp_path / "bridge_demo"
        compile_proc = subprocess.run(
            [cc, "-O2", "-o", str(binary), "native/bridge_client.c"],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert compile_proc.returncode == 0, compile_proc.stderr
        host, port = server.address
        proc = subprocess.run(
            [str(binary), host, str(port)],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "QUICKSTART PASS" in proc.stdout
        for name in ("alice", "bob", "carol"):
            assert f"{name}: consensus YES" in proc.stdout
