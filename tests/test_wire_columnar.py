"""Zero-copy columnar wire ingest: parser twins, ring transport, and
columnar-vs-object server-path parity (the object path is the oracle)."""

import os
import time

import numpy as np
import pytest

from hashgraph_tpu import build_vote, native
from hashgraph_tpu.bridge import columnar as WC
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.bridge.server import BridgeServer
from hashgraph_tpu.protocol import compute_vote_hash
from hashgraph_tpu.signing.stub import StubConsensusSigner
from hashgraph_tpu.sync.snapshot import state_fingerprint
from hashgraph_tpu.wire import Proposal, Vote

NOW = 1_700_000_000


def _pack(rows):
    offsets = np.zeros(len(rows) + 1, np.int64)
    np.cumsum([len(r) for r in rows], out=offsets[1:])
    return np.frombuffer(b"".join(rows) or b"\0", np.uint8), offsets


def _vote(i=1, **kw):
    kw.setdefault("vote_id", i)
    kw.setdefault("vote_owner", bytes([i]) * 20)
    kw.setdefault("proposal_id", 7)
    kw.setdefault("timestamp", NOW)
    kw.setdefault("vote", True)
    kw.setdefault("parent_hash", b"p" * 32)
    kw.setdefault("received_hash", b"r" * 32)
    kw.setdefault("vote_hash", b"h" * 32)
    kw.setdefault("signature", b"s" * 65)
    return Vote(**kw)


class TestColumnParser:
    def test_canonical_vote_parses_flag1_with_exact_columns(self):
        vote = _vote(3, timestamp=123456789, vote=True)
        raw = vote.encode()
        data, offsets = _pack([raw])
        cols, flags = WC.parse_vote_columns_py(data, offsets)
        assert flags.tolist() == [1]
        c = cols[0]
        assert c[WC.COL_VOTE_ID] == 3
        assert c[WC.COL_PID] == 7
        assert c[WC.COL_TS] == 123456789
        assert c[WC.COL_VALUE] == 1
        buf = data.tobytes()
        assert buf[c[WC.COL_OWNER_OFF]:c[WC.COL_OWNER_OFF] + c[WC.COL_OWNER_LEN]] == vote.vote_owner
        assert buf[c[WC.COL_SIG_OFF]:c[WC.COL_SIG_OFF] + c[WC.COL_SIG_LEN]] == vote.signature
        # The signing payload is a PREFIX of canonical wire bytes.
        assert raw[:c[WC.COL_SIGN_LEN]] == vote.signing_payload()

    def test_absent_fields_are_canonical_with_zero_lengths(self):
        vote = Vote(vote_id=5)  # everything else default/empty
        raw = vote.encode()
        data, offsets = _pack([raw])
        cols, flags = WC.parse_vote_columns_py(data, offsets)
        assert flags.tolist() == [1]
        assert cols[0][WC.COL_OWNER_LEN] == 0
        assert cols[0][WC.COL_SIG_LEN] == 0
        assert cols[0][WC.COL_SIGN_LEN] == len(raw)

    @pytest.mark.parametrize(
        "raw",
        [
            b"\xa0\x01\x00",          # field 20 with value 0 (non-canonical)
            b"\xa0\x01\x80\x00",      # non-minimal varint
            b"\xaa\x01\x00",          # empty LEN field (canonical omits)
            b"\xc0\x01\x02",          # bool field with value 2
            b"\xa0",                  # truncated tag
            b"\xaa\x01\x05ab",        # LEN overruns the row
            b"\x08\x01",              # unknown field number
            _vote(1).encode() + b"\x01",  # trailing garbage
            _vote(1).encode()[:-3],   # truncated signature field
        ],
    )
    def test_non_canonical_rows_flag_zero(self, raw):
        data, offsets = _pack([raw])
        cols, flags = WC.parse_vote_columns_py(data, offsets)
        assert flags.tolist() == [0]

    def test_out_of_order_fields_flag_zero_but_decode_still_works(self):
        # Swap two fields: Vote.decode accepts it (last-wins protobuf
        # semantics), the strict parser must NOT (the re-encoded
        # signing payload would differ from the wire prefix).
        reordered = b"\xb0\x01\x07" + b"\xa0\x01\x03"  # pid then vote_id
        assert Vote.decode(reordered).proposal_id == 7
        data, offsets = _pack([reordered])
        _, flags = WC.parse_vote_columns_py(data, offsets)
        assert flags.tolist() == [0]

    @pytest.mark.skipif(not native.available(), reason="native runtime absent")
    def test_native_and_python_parsers_are_output_identical(self):
        rows = [
            _vote(i, timestamp=NOW + i, vote=bool(i % 2)).encode()
            for i in range(1, 9)
        ]
        rows += [
            b"",
            b"\xa0\x01\x00",
            os.urandom(24),
            _vote(1).encode()[:-2],
            Vote(vote_owner=b"x").encode(),
            b"\xa0\x01\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01",  # u64 max
        ]
        data, offsets = _pack(rows)
        cols_n, flags_n = native.parse_vote_columns(data, offsets)
        cols_p, flags_p = WC.parse_vote_columns_py(data, offsets)
        assert flags_n.tolist() == flags_p.tolist()
        ok = flags_n.astype(bool)
        assert np.array_equal(cols_n[ok], cols_p[ok])

    def test_vote_hash_columns_matches_compute_vote_hash(self):
        votes = [
            _vote(i, received_hash=b"", parent_hash=bytes([i]) * 32)
            for i in range(1, 6)
        ]
        rows = [v.encode() for v in votes]
        data, offsets = _pack(rows)
        cols, flags = WC.parse_vote_columns(data, offsets)
        assert flags.all()
        digests = WC.vote_hash_columns(data, cols)
        for i, vote in enumerate(votes):
            assert digests[i].tobytes() == compute_vote_hash(vote)


class TestShmRing:
    def test_roundtrip_wrap_and_full(self):
        from hashgraph_tpu.gossip.shm import ShmRing, shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        ring = ShmRing.create(64)
        try:
            assert ring.try_write([b"hello", b"world"], 10)
            assert ring.read_available() == b"helloworld"
            for k in range(20):  # force wraparound repeatedly
                payload = bytes([k]) * 40
                assert ring.try_write([payload], 40)
                assert ring.read_available() == payload
            # The kernel rounds the segment up to a page: fill the REAL
            # capacity exactly, then one more byte must refuse whole.
            cap = ring.capacity
            assert ring.try_write([b"x" * cap], cap)
            assert not ring.try_write([b"y"], 1)  # full: all-or-nothing
            drained = b""
            while True:
                chunk = ring.read_available()
                if chunk is None:
                    break
                drained += chunk
            assert drained == b"x" * cap
        finally:
            ring.close()

    def test_attach_sees_creator_writes(self):
        from hashgraph_tpu.gossip.shm import ShmRing, shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        a = ShmRing.create(128)
        b = ShmRing.attach(a.name)
        try:
            assert a.try_write([b"abc"], 3)
            assert b.read_available() == b"abc"
        finally:
            b.close()
            a.close()


class _Harness:
    """Two embedded servers fed IDENTICAL frames: wire_columnar on/off.
    Every dispatch asserts byte-identical responses — the object path is
    the parity oracle for the columnar fast path."""

    def __init__(self):
        self.columnar = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=64,
            voter_capacity=24, wire_columnar=True,
        )
        self.objects = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=64,
            voter_capacity=24, wire_columnar=False,
        )
        for server in (self.columnar, self.objects):
            server.start_embedded()
        self.peer_ids = [
            P.Cursor(self._both(P.OP_ADD_PEER, P.u8(32) + b"\x11" * 32)).u32()
        ]

    def _both(self, opcode, payload) -> bytes:
        sc, oc = (
            self.columnar.dispatch_frame(opcode, payload),
            self.objects.dispatch_frame(opcode, payload),
        )
        assert sc == oc, f"parity break on opcode {opcode}: {sc} != {oc}"
        assert sc[0] == P.STATUS_OK, sc
        return sc[1]

    def both_raw(self, opcode, payload):
        """Dispatch to both and require byte-identical (status, body)."""
        sc = self.columnar.dispatch_frame(opcode, payload)
        oc = self.objects.dispatch_frame(opcode, payload)
        assert sc == oc, f"parity break on opcode {opcode}: {sc} != {oc}"
        return sc

    def deliver_proposal(self, scope: str, proposal: Proposal):
        self._both(
            P.OP_PROCESS_PROPOSAL,
            P.u32(self.peer_ids[0]) + P.string(scope) + P.u64(NOW)
            + P.blob(proposal.encode()),
        )

    def fingerprints_equal(self) -> bool:
        pid = self.peer_ids[0]
        return state_fingerprint(
            self.columnar.peer_engine(pid)
        ) == state_fingerprint(self.objects.peer_engine(pid))

    def stop(self):
        self.columnar.stop()
        self.objects.stop()


def _chain(proposal: Proposal, signers, value=True):
    """Signed chained wire votes for ``proposal`` (mutates its votes)."""
    out = []
    for signer in signers:
        vote = build_vote(proposal, value, signer, NOW + 1)
        proposal.votes.append(vote)
        out.append(vote.encode())
    return out


def _proposal(scope_tag: str, voters: int = 20) -> Proposal:
    return Proposal(
        name=f"p-{scope_tag}",
        payload=b"x",
        proposal_id=(abs(hash(scope_tag)) % 1_000_000) + 1,
        proposal_owner=b"\x11" * 20,
        expected_voters_count=voters,
        timestamp=NOW,
        expiration_timestamp=NOW + 3_600,
        liveness_criteria_yes=True,
    )


@pytest.fixture(scope="module")
def harness():
    h = _Harness()
    yield h
    h.stop()


def _batch(h, scope, rows, now=NOW + 1):
    return h.both_raw(
        P.OP_VOTE_BATCH,
        P.encode_vote_batch(now, [(h.peer_ids[0], scope, rows)]),
    )


class TestServerPathParity:
    def test_valid_chain_and_decision(self, harness):
        proposal = _proposal("valid", voters=5)
        harness.deliver_proposal("valid", proposal)
        rows = _chain(proposal, [StubConsensusSigner(bytes([i]) * 20) for i in range(1, 7)])
        status, body = _batch(harness, "valid", rows)
        assert status == P.STATUS_OK
        c = P.Cursor(body)
        assert c.u32() == 6
        assert harness.fingerprints_equal()

    def test_mixed_bad_rows_duplicates_and_junk(self, harness):
        proposal = _proposal("mixed")
        harness.deliver_proposal("mixed", proposal)
        signers = [StubConsensusSigner(bytes([40 + i]) * 20) for i in range(6)]
        rows = _chain(proposal, signers)
        _batch(harness, "mixed", rows[:4])
        flipped = bytearray(rows[4])
        flipped[-1] ^= 0xFF  # signature byte flip: INVALID_VOTE_SIGNATURE
        follow_up = [
            bytes(flipped),
            rows[0],          # duplicate of an accepted vote
            rows[4][:9],      # truncated row (non-canonical -> 241 path)
            os.urandom(40),   # junk row
            rows[5],          # dangles: its predecessor was rejected
        ]
        status, body = _batch(harness, "mixed", follow_up)
        assert status == P.STATUS_OK
        assert harness.fingerprints_equal()

    def test_cross_frame_dangling_guard_stays_armed(self, harness):
        # Drop frame 2 of a chain: frame 3's votes dangle and must be
        # rejected IDENTICALLY on both paths — the wire path's chain
        # continuity state keeps the guard armed past the first frame.
        proposal = _proposal("dangle")
        harness.deliver_proposal("dangle", proposal)
        signers = [StubConsensusSigner(bytes([80 + i]) * 20) for i in range(9)]
        rows = _chain(proposal, signers)
        _batch(harness, "dangle", rows[:3])
        status, body = _batch(harness, "dangle", rows[6:])  # frames 4-6 dropped
        c = P.Cursor(body)
        n = c.u32()
        codes = list(c.raw(n))
        from hashgraph_tpu.errors import StatusCode

        assert codes == [int(StatusCode.RECEIVED_HASH_MISMATCH)] * 3
        assert harness.fingerprints_equal()
        # The repair path (deliver watermark) must still be able to
        # extend the wire-retained session with the missing suffix.
        status, body = harness.both_raw(
            P.OP_DELIVER_PROPOSALS,
            P.encode_deliver_proposals(
                harness.peer_ids[0], [("dangle", proposal.encode())], NOW + 1
            ),
        )
        assert status == P.STATUS_OK
        c = P.Cursor(body)
        assert c.u32() == 1
        assert list(c.raw(1)) == [int(StatusCode.OK)]
        assert harness.fingerprints_equal()

    def test_empty_owner_hash_signature_precedence(self, harness):
        proposal = _proposal("empties")
        harness.deliver_proposal("empties", proposal)
        base = build_vote(
            proposal, True, StubConsensusSigner(b"\x60" * 20), NOW + 1
        )
        no_owner = base.clone()
        no_owner.vote_owner = b""
        no_hash = base.clone()
        no_hash.vote_hash = b""
        no_sig = base.clone()
        no_sig.signature = b""
        bad_hash = base.clone()
        bad_hash.vote_hash = b"\x01" * 32
        expired = build_vote(
            proposal, True, StubConsensusSigner(b"\x61" * 20), NOW + 1
        )
        rows = [v.encode() for v in (no_owner, no_hash, no_sig, bad_hash, expired)]
        status, body = _batch(harness, "empties", rows, now=NOW + 10_000)
        c = P.Cursor(body)
        n = c.u32()
        codes = list(c.raw(n))
        from hashgraph_tpu.errors import StatusCode

        assert codes[:4] == [
            int(StatusCode.EMPTY_VOTE_OWNER),
            int(StatusCode.EMPTY_VOTE_HASH),
            int(StatusCode.EMPTY_SIGNATURE),
            int(StatusCode.INVALID_VOTE_HASH),
        ]
        assert harness.fingerprints_equal()

    def test_unknown_scope_and_unknown_peer(self, harness):
        vote = _vote(1)
        status, body = _batch(harness, "never-created", [vote.encode()])
        c = P.Cursor(body)
        n = c.u32()
        from hashgraph_tpu.errors import StatusCode

        assert list(c.raw(n)) == [int(StatusCode.SESSION_NOT_FOUND)]
        status, body = harness.both_raw(
            P.OP_VOTE_BATCH,
            P.encode_vote_batch(NOW, [(999, "s", [vote.encode()])]),
        )
        c = P.Cursor(body)
        assert list(c.raw(c.u32())) == [P.STATUS_UNKNOWN_PEER]

    def test_malformed_frames_report_identical_errors(self, harness):
        good = P.encode_vote_batch(NOW, [(harness.peer_ids[0], "s", [b"x"])])
        for payload in (
            b"",                      # no header at all
            good[:6],                 # truncated header
            good[:-1],                # truncated vote region
            P.u64(NOW) + P.u32(2) + P.u32(1) + P.string("s") + P.u32(50),
            # count overflow: group claims 2^31 votes
            P.u64(NOW) + P.u32(1) + P.u32(1) + P.string("s")
            + P.u32(0x7FFFFFFF),
        ):
            status_pair = harness.both_raw(P.OP_VOTE_BATCH, payload)
            assert status_pair[0] == P.STATUS_BAD_REQUEST


class TestShmTransportEndToEnd:
    def test_vote_batch_over_shm_ring(self):
        from hashgraph_tpu.gossip import GossipNode
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=32, voter_capacity=20
        )
        server.start()
        node = None
        try:
            from hashgraph_tpu.bridge.client import BridgeClient

            client = BridgeClient(*server.address)
            peer_id, _ = client.add_peer(b"\x33" * 32)
            pid, blob = client.create_proposal(
                peer_id, "s", NOW, "p", b"x", 17, 3_600
            )
            proposal = Proposal.decode(blob)
            rows = _chain(
                proposal,
                [StubConsensusSigner(os.urandom(20)) for _ in range(16)],
            )
            node = GossipNode(
                "shm-driver", fanout=None, flush_votes=64,
                shm_ring_bytes=1 << 20,
            )
            node.add_peer("p0", *server.address, peer_id)
            assert node.transport.channel("p0").shm_tx is not None
            node.submit_votes("s", pid, rows, NOW + 1, local=False)
            report = node.drain()
            assert report["acked"] == 16
            assert report["failed_frames"] == 0
            client.close()
        finally:
            if node is not None:
                node.close()
            server.stop()

    def test_attach_refused_keeps_tcp_lane(self):
        from hashgraph_tpu.gossip import GossipNode

        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        node = None
        try:
            # Transport without shm configured: no attach attempted, TCP
            # lane only — and everything still works.
            node = GossipNode("tcp-driver", fanout=None)
            from hashgraph_tpu.bridge.client import BridgeClient

            client = BridgeClient(*server.address)
            peer_id, _ = client.add_peer(b"\x44" * 32)
            node.add_peer("p0", *server.address, peer_id)
            assert node.transport.channel("p0").shm_tx is None
            client.close()
        finally:
            if node is not None:
                node.close()
            server.stop()

    def test_closed_ring_raises_valueerror(self):
        from hashgraph_tpu.gossip.shm import ShmRing, shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        ring = ShmRing.create(64)
        ring.close()
        with pytest.raises(ValueError):
            ring.read_available()
        with pytest.raises(ValueError):
            ring.try_write([b"x"], 1)

    def _shm_transport(self, server, ring_bytes=4096):
        from hashgraph_tpu.gossip.transport import GossipTransport

        transport = GossipTransport(shm_ring_bytes=ring_bytes)
        channel = transport.connect("p0", *server.address)
        if channel.shm_tx is None:
            transport.close()
            pytest.skip("shm attach unavailable")
        return transport, channel

    def test_oversize_frame_rides_tcp_lane(self):
        """A frame the ring can NEVER hold must not shed forever: it
        skips the shm lane and rides the TCP control lane instead."""
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        try:
            transport, channel = self._shm_transport(server)
            try:
                payload = b"z" * (channel.shm_tx.capacity + 4096)
                future = transport.try_request("p0", P.OP_PING, payload)
                assert future is not None, "oversize frame was shed"
                assert future.result(10).u32() == P.PROTOCOL_VERSION
                # The shm lane itself stays live for fitting frames.
                small = transport.try_request("p0", P.OP_PING)
                assert small is not None
                assert small.result(10).u32() == P.PROTOCOL_VERSION
            finally:
                transport.close()
        finally:
            server.stop()

    def _assert_channel_dies_typed(self, transport, channel):
        from hashgraph_tpu.bridge.client import (
            BridgeConnectionLost, BridgeError,
        )

        deadline = time.monotonic() + 10
        while channel.alive and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not channel.alive, "corrupt shm stream left the channel up"
        future = transport.try_request("p0", P.OP_PING)
        with pytest.raises((BridgeConnectionLost, BridgeError)):
            future.result(10)

    def test_corrupt_c2s_stream_kills_connection(self):
        """Garbage in the request ring must kill the WHOLE connection
        (server side detects), never silently stop serving the ring
        while the client keeps writing into it."""
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        try:
            transport, channel = self._shm_transport(server)
            try:
                # Length prefix 0 is structurally impossible (< tagged
                # minimum of 5): framing is unrecoverable.
                assert channel.shm_tx.try_write([b"\x00" * 4], 4)
                self._assert_channel_dies_typed(transport, channel)
            finally:
                transport.close()
        finally:
            server.stop()

    def test_corrupt_s2c_stream_kills_connection(self):
        """Garbage in the response ring kills the channel typed on the
        client side (rx thread detects)."""
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        try:
            transport, channel = self._shm_transport(server)
            try:
                assert channel.shm_rx.try_write([b"\x00" * 4], 4)
                self._assert_channel_dies_typed(transport, channel)
            finally:
                transport.close()
        finally:
            server.stop()

    def test_oversize_response_rides_tcp_lane(self):
        """A response larger than the s2c ring can EVER hold must come
        back on the TCP control lane (corr ids match across lanes) —
        spinning on the full ring would hold the server's tx lock
        forever and wedge every later response on the connection."""
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        try:
            transport, channel = self._shm_transport(server, ring_bytes=2048)
            try:
                # GET_METRICS: tiny request (rides the ring), multi-KB
                # process-global registry response (can never fit it).
                future = transport.try_request("p0", P.OP_GET_METRICS)
                assert future is not None
                text = future.result(10).blob()
                assert len(text) > channel.shm_rx.capacity
                assert b"hashgraph" in text
                # The shm lane itself stays live for fitting responses.
                small = transport.try_request("p0", P.OP_PING)
                assert small.result(10).u32() == P.PROTOCOL_VERSION
            finally:
                transport.close()
        finally:
            server.stop()

    def test_mutating_frames_never_split_across_lanes(self):
        """Ordered (mutating) opcodes stay on ONE lane: while any is
        pending on TCP, later mutating frames follow it there; an
        oversize mutating frame is admitted to TCP only once the ring
        is drained (sheds until then). Read-only traffic is unaffected."""
        from hashgraph_tpu.gossip.shm import shm_available

        if not shm_available():
            pytest.skip("shared memory unavailable")
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        server.start()
        try:
            transport, channel = self._shm_transport(server)
            try:
                # Simulate a mutating frame already pending on TCP.
                with channel.lock:
                    channel.tcp_mutating.add(999_999)
                    corr1 = channel.next_corr
                f1 = transport.try_request("p0", P.OP_PROCESS_VOTE, b"junk")
                assert f1 is not None
                with channel.lock:
                    assert corr1 not in channel.shm_inflight  # rode TCP
                    assert corr1 in channel.tcp_mutating
                    channel.tcp_mutating.discard(999_999)
                with pytest.raises(Exception):
                    f1.result(10)  # junk payload: typed wire error
                # Response received -> the set drains -> mutating frames
                # return to the ring.
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    with channel.lock:
                        if not channel.tcp_mutating:
                            break
                    time.sleep(0.01)
                with channel.lock:
                    assert not channel.tcp_mutating
                    corr2 = channel.next_corr
                f2 = transport.try_request("p0", P.OP_PROCESS_VOTE, b"junk")
                assert f2 is not None
                with channel.lock:
                    rode_ring = corr2 in channel.shm_inflight
                assert rode_ring or f2.done()  # back on the shm lane

                # Oversize mutating frame: gated on a drained ring.
                class _RingProxy:
                    def __init__(self, ring, pending):
                        self._ring = ring
                        self.pending = pending
                        self.capacity = ring.capacity

                    def try_write(self, segments, total):
                        return self._ring.try_write(segments, total)

                    def pending_bytes(self):
                        return self.pending

                    def close(self):
                        self._ring.close()

                real = channel.shm_tx
                proxy = _RingProxy(real, pending=64)
                with channel.lock:
                    channel.shm_tx = proxy
                big = b"z" * (real.capacity + 1024)
                assert transport.try_request(
                    "p0", P.OP_VOTE_BATCH, big
                ) is None  # shed: server has not consumed the ring yet
                proxy.pending = 0
                with channel.lock:
                    corr3 = channel.next_corr
                f3 = transport.try_request("p0", P.OP_VOTE_BATCH, big)
                assert f3 is not None  # drained ring: admitted to TCP
                with channel.lock:
                    assert corr3 in channel.tcp_mutating
                    assert corr3 not in channel.shm_inflight
                    channel.shm_tx = real
                with pytest.raises(Exception):
                    f3.result(10)
            finally:
                transport.close()
        finally:
            server.stop()


class TestShmAttachCleanup:
    def test_failed_second_attach_unmaps_the_first_ring(self, monkeypatch):
        """c2s attaches, s2c raises: the server must close the already
        mapped c2s ring instead of leaking one segment per bad attempt."""
        import threading
        from types import SimpleNamespace

        from hashgraph_tpu.gossip import shm as shm_mod

        closed = []

        class _FakeRing:
            def __init__(self, name):
                self.name = name

            def close(self):
                closed.append(self.name)

            @classmethod
            def attach(cls, name):
                if name == "s2c-bogus":
                    raise OSError("no such segment")
                return cls(name)

        monkeypatch.setattr(shm_mod, "ShmRing", _FakeRing)
        monkeypatch.setattr(shm_mod, "shm_available", lambda: True)
        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=8, voter_capacity=4
        )
        sent = []
        conn = SimpleNamespace(sendall=sent.append)
        state = SimpleNamespace(write_lock=threading.Lock())
        cursor = P.Cursor(
            P.u32(1024) + P.string("c2s-ok") + P.string("s2c-bogus")
        )
        assert server._handle_shm_attach(conn, state, 7, cursor) is True
        assert closed == ["c2s-ok"]
        status, corr, _payload = P.parse_frame(sent[0][4:], tagged=True)
        assert status == P.STATUS_BAD_REQUEST
        assert corr == 7


class TestDurableWireReplay:
    """KIND_WIRE_COLUMNAR: durable wire ingest logs its own record kind
    and replays through the WIRE path, so a recovered peer keeps the
    wire-validated retention (``wire_only``) and the cross-frame
    dangling-vote guard its non-crashed twins have — replaying through
    plain columnar ingest would silently demote both."""

    def _engine(self, identity=b"\x77" * 20):
        from hashgraph_tpu.engine import TpuConsensusEngine

        return TpuConsensusEngine(
            StubConsensusSigner(identity), capacity=32, voter_capacity=24
        )

    @staticmethod
    def _wire_frame(rows):
        data, offsets = _pack(rows)
        cols, flags = WC.parse_vote_columns(data, offsets)
        assert bool(flags.all()), "test rows must be canonical"
        return (
            ["wire-replay"], np.zeros(len(rows), np.int64), cols, data, offsets
        )

    def test_guard_survives_crash_recovery(self, tmp_path):
        from hashgraph_tpu.errors import StatusCode
        from hashgraph_tpu.wal import DurableEngine, replay, scan
        from hashgraph_tpu.wal import format as WF

        proposal = _proposal("wire-replay", voters=20)
        signers = [StubConsensusSigner(bytes([120 + i]) * 20) for i in range(9)]
        durable = DurableEngine(
            self._engine(), str(tmp_path / "wal"), fsync_policy="off"
        )
        twin = self._engine(b"\x78" * 20)
        for engine in (durable, twin):
            engine.ingest_proposals([("wire-replay", proposal.clone())], NOW)
        rows = _chain(proposal, signers)

        frame1 = self._wire_frame(rows[:3])
        got_d = durable.ingest_wire_columnar(*frame1, NOW + 1)
        got_t = twin.ingest_wire_columnar(*frame1, NOW + 1)
        assert list(got_d) == [int(StatusCode.OK)] * 3 == list(got_t)

        kinds = {kind for _, kind, _ in scan(str(tmp_path / "wal")).records}
        assert WF.KIND_WIRE_COLUMNAR in kinds
        assert WF.KIND_COLUMNAR not in kinds

        durable.abandon()
        recovered = self._engine()
        replay(str(tmp_path / "wal"), recovered)

        # Frames covering rows 3..5 never arrive: rows 6..8 dangle and
        # must reject IDENTICALLY on the recovered peer and the
        # never-crashed twin — this is exactly what broke when wire
        # records replayed through the permissive columnar path.
        dangling = self._wire_frame(rows[6:])
        got_r = recovered.ingest_wire_columnar(*dangling, NOW + 1)
        got_t = twin.ingest_wire_columnar(*dangling, NOW + 1)
        assert (
            list(got_r)
            == list(got_t)
            == [int(StatusCode.RECEIVED_HASH_MISMATCH)] * 3
        )

        # The repair path still works on the recovered session: the full
        # chain delivered through the watermark extends it to OK.
        statuses = recovered.deliver_proposals(
            [("wire-replay", proposal.clone())], NOW + 1
        )
        assert list(statuses) == [int(StatusCode.OK)]

    def test_mixed_accept_reject_frame_logs_only_accepted_rows(self, tmp_path):
        from hashgraph_tpu.errors import StatusCode
        from hashgraph_tpu.wal import DurableEngine, replay, scan
        from hashgraph_tpu.wal import format as WF

        proposal = _proposal("wire-replay", voters=20)
        signers = [StubConsensusSigner(bytes([150 + i]) * 20) for i in range(4)]
        durable = DurableEngine(
            self._engine(), str(tmp_path / "wal"), fsync_policy="off"
        )
        durable.ingest_proposals([("wire-replay", proposal.clone())], NOW)
        rows = _chain(proposal, signers)
        bad = bytearray(rows[2])
        bad[-1] ^= 0xFF  # signature flip: INVALID_VOTE_SIGNATURE
        frame = self._wire_frame([rows[0], rows[1], bytes(bad)])
        got = durable.ingest_wire_columnar(*frame, NOW + 1)
        assert list(got) == [
            int(StatusCode.OK),
            int(StatusCode.OK),
            int(StatusCode.INVALID_VOTE_SIGNATURE),
        ]
        wire_records = [
            payload
            for _, kind, payload in scan(str(tmp_path / "wal")).records
            if kind == WF.KIND_WIRE_COLUMNAR
        ]
        assert len(wire_records) == 1
        _, _, _, blob, offsets = WF.decode_columnar(wire_records[0])
        assert len(offsets) - 1 == 2  # only the accepted rows
        assert blob == rows[0] + rows[1]

        durable.abandon()
        recovered = self._engine()
        replay(str(tmp_path / "wal"), recovered)
        # Replay re-accepts exactly the logged prefix: the next chained
        # vote (rows[2] with a good signature) extends it.
        frame2 = self._wire_frame([rows[2]])
        assert list(recovered.ingest_wire_columnar(*frame2, NOW + 1)) == [
            int(StatusCode.OK)
        ]


class TestWireBufSharing:
    """The frame's vote region is materialized as bytes ONCE and shared
    between the crypto prepass, the apply stage, and (durable) the WAL
    blob — the zero-copy receive path doesn't re-copy per stage."""

    def test_apply_reuses_the_prepass_copy(self):
        from hashgraph_tpu.engine import TpuConsensusEngine
        from hashgraph_tpu.errors import StatusCode

        engine = TpuConsensusEngine(
            StubConsensusSigner(b"\x66" * 20), capacity=16, voter_capacity=8
        )
        proposal = _proposal("buf-share", voters=10)
        engine.ingest_proposals([("buf-share", proposal.clone())], NOW)
        rows = _chain(
            proposal, [StubConsensusSigner(bytes([90 + i]) * 20) for i in range(3)]
        )
        data, offsets = _pack(rows)
        cols, flags = WC.parse_vote_columns(data, offsets)
        assert bool(flags.all())
        prepass = engine.wire_verify_begin(data, cols, offsets)
        shared = prepass.buf
        assert shared == data.tobytes()
        got = engine.ingest_wire_columnar(
            ["buf-share"], np.zeros(3, np.int64), cols, data, offsets, NOW + 1,
            _prepass=prepass,
        )
        assert list(got) == [int(StatusCode.OK)] * 3
        assert prepass.buf is shared  # reused, not recomputed

    def test_explicit_buf_wins_and_lands_on_the_prepass(self):
        from hashgraph_tpu.engine import TpuConsensusEngine
        from hashgraph_tpu.errors import StatusCode

        engine = TpuConsensusEngine(
            StubConsensusSigner(b"\x65" * 20), capacity=16, voter_capacity=8
        )
        proposal = _proposal("buf-share2", voters=10)
        engine.ingest_proposals([("buf-share2", proposal.clone())], NOW)
        rows = _chain(
            proposal, [StubConsensusSigner(bytes([95 + i]) * 20) for i in range(2)]
        )
        data, offsets = _pack(rows)
        cols, flags = WC.parse_vote_columns(data, offsets)
        caller_buf = data.tobytes()
        got = engine.ingest_wire_columnar(
            ["buf-share2"], np.zeros(2, np.int64), cols, data, offsets, NOW + 1,
            _buf=caller_buf,
        )
        assert list(got) == [int(StatusCode.OK)] * 2


class TestPreparedFallbackSentinel:
    """A reader-thread prepare that chose the object fallback must not
    be re-run on the serial lane: the sentinel carries the verdict, so a
    sustained stream of non-canonical frames pays ONE columnar parse
    attempt per frame, not two plus the object decode."""

    def test_lane_skips_reprepare_after_reader_fallback(self):
        from hashgraph_tpu.bridge.server import _PREP_FALLBACK
        from hashgraph_tpu.errors import StatusCode

        server = BridgeServer(
            signer_factory=StubConsensusSigner, capacity=16,
            voter_capacity=8, wire_columnar=True,
        )
        server.start_embedded()
        try:
            status, body = server.dispatch_frame(
                P.OP_ADD_PEER, P.u8(32) + b"\x33" * 32
            )
            assert status == P.STATUS_OK
            pid = P.Cursor(body).u32()
            proposal = _proposal("sentinel", voters=10)
            server.dispatch_frame(
                P.OP_PROCESS_PROPOSAL,
                P.u32(pid) + P.string("sentinel") + P.u64(NOW)
                + P.blob(proposal.encode()),
            )
            rows = _chain(
                proposal,
                [StubConsensusSigner(bytes([210 + i]) * 20) for i in range(2)],
            )
            rows.append(rows[-1][:9])  # truncated row -> object fallback
            payload = P.encode_vote_batch(NOW + 1, [(pid, "sentinel", rows)])

            # Reader-thread half: a non-canonical row yields the sentinel.
            prep = server._vote_batch_prepare(P.Cursor(payload)) or _PREP_FALLBACK
            assert prep is _PREP_FALLBACK

            calls = []
            orig = server._vote_batch_prepare
            server._vote_batch_prepare = lambda c: (calls.append(1), orig(c))[1]
            try:
                status, body = server._op_vote_batch(P.Cursor(payload), prep)
            finally:
                server._vote_batch_prepare = orig
            assert calls == []  # the lane went straight to the object path
            assert status == P.STATUS_OK
            c = P.Cursor(body)
            assert c.u32() == 3
            codes = list(c.raw(3))
            assert codes[:2] == [int(StatusCode.OK)] * 2
            assert codes[2] == 241  # undecodable row
        finally:
            server.stop()
