"""Gossip redelivery + incremental chain growth: the deliver_proposals
create-or-extend surface and its validated-chain watermark.

Tier-1 smoke for the amortization layer (ISSUE 4): one redelivery wave
with the stub signer exercises cache hits, the watermark suffix path,
fork/truncation rejection, and bench.py's redelivery workload shape —
without ``slow`` markers or real ECDSA.
"""

import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.wire import Proposal

from common import NOW

OK = int(StatusCode.OK)
EXISTS = int(StatusCode.PROPOSAL_ALREADY_EXIST)


def make_engine(cache="default", voters=16, **kwargs):
    return TpuConsensusEngine(
        StubConsensusSigner(b"\x42" * 20),
        capacity=32,
        voter_capacity=voters,
        verify_cache=cache,
        **kwargs,
    )


def make_chain(n_votes=6, expected=12, scope="s", engine=None):
    """(engine, base proposal, fully grown chain) with ``n_votes`` chained
    votes by distinct stub signers (chain-linked via build_vote)."""
    engine = engine if engine is not None else make_engine()
    proposal = engine.create_proposal(
        scope,
        CreateProposalRequest(
            name="p",
            payload=b"x",
            proposal_owner=b"o",
            expected_voters_count=expected,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        ),
        NOW,
    )
    chain = proposal.clone()
    for i in range(n_votes):
        signer = StubConsensusSigner(bytes([i + 1]) * 20)
        chain.votes.append(build_vote(chain, bool(i % 2), signer, NOW + 1 + i))
    return engine, proposal, chain


def grown(chain, k):
    p = chain.clone()
    p.votes = [v.clone() for v in chain.votes[:k]]
    return p


class TestDeliverProposals:
    def test_unknown_pid_registers(self):
        _, _, chain = make_chain()
        receiver = make_engine()
        [code] = receiver.deliver_proposals([("s", grown(chain, 3))], NOW + 20)
        assert code == OK
        got = receiver.get_proposal("s", chain.proposal_id)
        assert [v.vote_hash for v in got.votes] == [
            v.vote_hash for v in chain.votes[:3]
        ]

    def test_incremental_growth_extends_along_watermark(self):
        _, _, chain = make_chain(n_votes=6)
        receiver = make_engine()
        for k in range(1, len(chain.votes) + 1):
            [code] = receiver.deliver_proposals(
                [("s", grown(chain, k))], NOW + 20
            )
            assert code == OK, (k, code)
        got = receiver.get_proposal("s", chain.proposal_id)
        assert [v.vote_hash for v in got.votes] == [
            v.vote_hash for v in chain.votes
        ]

    def test_exact_redelivery_is_already_exist(self):
        _, _, chain = make_chain()
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 4), NOW + 20) == OK
        assert (
            receiver.deliver_proposal("s", grown(chain, 4), NOW + 21) == EXISTS
        )

    def test_truncated_chain_rejected(self):
        _, _, chain = make_chain()
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 4), NOW + 20) == OK
        assert (
            receiver.deliver_proposal("s", grown(chain, 2), NOW + 21) == EXISTS
        )
        assert len(receiver.get_proposal("s", chain.proposal_id).votes) == 4

    def test_expired_extension_fails_fast_without_crypto(self):
        """Extensions of an expired session are rejected BEFORE the
        signature prepass, matching the expiry fail-fasts on the
        process_incoming_proposal / ingest_proposals entry points: an
        attacker redelivering grown chains of a dead session must not buy
        ECDSA work or churn the shared cache's LRU."""

        class CountingSigner(StubConsensusSigner):
            calls = 0

            @classmethod
            def verify(cls, identity, payload, signature):
                cls.calls += 1
                return super().verify(identity, payload, signature)

        engine = TpuConsensusEngine(
            CountingSigner(b"\x42" * 20),
            capacity=32,
            voter_capacity=16,
            verify_cache="default",
        )
        _, _, chain = make_chain(engine=engine)
        assert engine.deliver_proposal("s", grown(chain, 3), NOW + 20) == OK
        cached = len(engine.verify_cache())
        CountingSigner.calls = 0
        expiry = engine.get_proposal("s", chain.proposal_id).expiration_timestamp
        late = expiry + 1
        [code] = engine.deliver_proposals([("s", grown(chain, 6))], late)
        assert code == int(StatusCode.PROPOSAL_EXPIRED)
        assert CountingSigner.calls == 0
        assert len(engine.verify_cache()) == cached
        # The accepted prefix is untouched.
        assert len(engine.get_proposal("s", chain.proposal_id).votes) == 3

    def test_fork_before_watermark_rejected(self):
        _, proposal, chain = make_chain()
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 4), NOW + 20) == OK
        fork = grown(chain, 5)
        # Replace vote 2 with a differently-signed one: the prefix no
        # longer matches the accepted chain, so nothing applies.
        fork.votes[2] = build_vote(
            proposal, True, StubConsensusSigner(b"\x91" * 20), NOW + 40
        )
        assert receiver.deliver_proposal("s", fork, NOW + 41) == EXISTS
        got = receiver.get_proposal("s", chain.proposal_id)
        assert [v.vote_hash for v in got.votes] == [
            v.vote_hash for v in chain.votes[:4]
        ]

    def test_bad_signature_suffix_rejected_without_applying(self):
        _, _, chain = make_chain()
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 3), NOW + 20) == OK
        bad = grown(chain, 5)
        bad.votes[4].signature = b"\x00" * 32
        code = receiver.deliver_proposal("s", bad, NOW + 21)
        assert code == int(StatusCode.INVALID_VOTE_SIGNATURE)
        # All-or-nothing: vote 3 (valid) must not have landed either.
        assert len(receiver.get_proposal("s", chain.proposal_id).votes) == 3
        # The honest grown chain still applies afterwards (negative cache
        # holds the forged key only).
        assert receiver.deliver_proposal("s", grown(chain, 5), NOW + 22) == OK

    def test_bad_suffix_link_rejected(self):
        _, _, chain = make_chain()
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 3), NOW + 20) == OK
        bad = grown(chain, 5)
        bad.votes[4].received_hash = b"\x13" * 32
        bad.votes[4].vote_hash = b""
        from hashgraph_tpu.protocol import compute_vote_hash

        bad.votes[4].vote_hash = compute_vote_hash(bad.votes[4])
        signer = StubConsensusSigner(bad.votes[4].vote_owner)
        bad.votes[4].signature = signer.sign(bad.votes[4].signing_payload())
        code = receiver.deliver_proposal("s", bad, NOW + 21)
        assert code == int(StatusCode.RECEIVED_HASH_MISMATCH)
        assert len(receiver.get_proposal("s", chain.proposal_id).votes) == 3

    def test_mixed_batch_fresh_extension_redelivery(self):
        engine_a, _, chain_a = make_chain(scope="a")
        _, _, chain_b = make_chain(scope="b")
        receiver = make_engine()
        assert receiver.deliver_proposal("a", grown(chain_a, 2), NOW + 20) == OK
        codes = receiver.deliver_proposals(
            [
                ("a", grown(chain_a, 4)),  # extension
                ("b", grown(chain_b, 3)),  # fresh registration
                ("a", grown(chain_a, 4)),  # redelivery (same batch!)
            ],
            NOW + 21,
        )
        assert codes == [OK, OK, EXISTS]

    def test_batch_equals_sequential(self):
        """A batch delivery is definitionally the same as sequential
        deliveries — load-bearing for WAL record splitting: a chunked
        KIND_DELIVER record replays as consecutive smaller batches."""
        _, _, chain = make_chain(n_votes=4)
        batched = make_engine()
        codes = batched.deliver_proposals(
            [("s", grown(chain, 2)), ("s", grown(chain, 4))], NOW + 20
        )
        assert codes == [OK, OK]  # create, then extend — not ALREADY_EXIST
        sequential = make_engine()
        assert sequential.deliver_proposal("s", grown(chain, 2), NOW + 20) == OK
        assert sequential.deliver_proposal("s", grown(chain, 4), NOW + 20) == OK
        a = batched.export_session("s", chain.proposal_id)
        b = sequential.export_session("s", chain.proposal_id)
        assert [v.vote_hash for v in a.proposal.votes] == [
            v.vote_hash for v in b.proposal.votes
        ]
        assert len(a.proposal.votes) == 4

    def test_configs_must_align(self):
        receiver = make_engine()
        with pytest.raises(ValueError):
            receiver.deliver_proposals([], NOW, configs=[None])

    def test_oracle_parity_final_session(self):
        """The incrementally-extended session equals the one a fresh
        engine builds from the final chain in one delivery."""
        _, _, chain = make_chain(n_votes=6)
        incremental = make_engine()
        for k in range(1, 7):
            assert (
                incremental.deliver_proposal("s", grown(chain, k), NOW + 20)
                == OK
            )
        oneshot = make_engine()
        assert oneshot.deliver_proposal("s", grown(chain, 6), NOW + 20) == OK
        a = incremental.export_session("s", chain.proposal_id)
        b = oneshot.export_session("s", chain.proposal_id)
        assert [v.vote_hash for v in a.proposal.votes] == [
            v.vote_hash for v in b.proposal.votes
        ]
        assert a.state == b.state
        assert set(a.votes) == set(b.votes)

    def test_decision_fires_on_extension(self):
        """A suffix that crosses quorum decides the session — the
        extension path applies through the real vote pipeline, decision
        kernel included."""
        _, _, chain = make_chain(n_votes=6, expected=6)
        receiver = make_engine()
        assert receiver.deliver_proposal("s", grown(chain, 3), NOW + 20) == OK
        assert receiver.get_consensus_result("s", chain.proposal_id) is None
        code = receiver.deliver_proposal("s", grown(chain, 6), NOW + 21)
        assert code in (OK, int(StatusCode.ALREADY_REACHED))
        oracle = make_engine()
        assert oracle.deliver_proposal("s", grown(chain, 6), NOW + 20) == OK
        assert receiver.get_consensus_result(
            "s", chain.proposal_id
        ) == oracle.get_consensus_result("s", chain.proposal_id)


class TestCacheOnOffEquivalence:
    def test_one_redelivery_wave_smoke(self):
        """The bench.py redelivery shape in miniature, stub-signed: grow a
        chain delivery by delivery, then redeliver every vote — cache-on
        and cache-off engines must report identical statuses and end in
        identical sessions."""
        _, _, chain = make_chain(n_votes=5)
        results = {}
        for label, cache in (("on", "default"), ("off", None)):
            receiver = make_engine(cache)
            codes = []
            for k in range(1, 6):
                codes.append(
                    receiver.deliver_proposal("s", grown(chain, k), NOW + 20)
                )
            # Redelivery wave through the vote path (embedder fallback).
            wave = [("s", v.clone()) for v in chain.votes]
            codes.append([int(s) for s in receiver.ingest_votes(wave, NOW + 30)])
            session = receiver.export_session("s", chain.proposal_id)
            results[label] = (
                codes,
                [v.vote_hash for v in session.proposal.votes],
                session.state,
            )
        assert results["on"] == results["off"]


class TestDurableDeliver:
    def test_wal_replay_preserves_extensions(self, tmp_path):
        """deliver_proposals logs KIND_DELIVER: a crash after incremental
        extensions replays to the identical chain (a plain-proposals
        record would replay as ingest and drop every suffix)."""
        from hashgraph_tpu.wal import DurableEngine, replay

        _, _, chain = make_chain(n_votes=5)
        wal_dir = str(tmp_path / "wal")
        durable = DurableEngine(make_engine(), wal_dir)
        for k in range(1, 6):
            assert (
                durable.deliver_proposal("s", grown(chain, k), NOW + 20) == OK
            )
        live = durable.export_session("s", chain.proposal_id)
        durable.close()

        recovered = make_engine()
        stats = replay(wal_dir, recovered)
        assert not stats.errors
        session = recovered.export_session("s", chain.proposal_id)
        assert [v.vote_hash for v in session.proposal.votes] == [
            v.vote_hash for v in live.proposal.votes
        ]
        assert session.state == live.state


class TestProcessIncomingProposalCache:
    def test_scalar_path_uses_cache(self):
        """process_incoming_proposal (the bridge opcode path) consults the
        cache for embedded chains — second engine sharing the cache skips
        every verify (observable via identical outcomes; call counting
        lives in test_verify_cache)."""
        from hashgraph_tpu.engine import VerifiedVoteCache

        _, _, chain = make_chain(n_votes=4)
        shared = VerifiedVoteCache()
        r1 = make_engine(shared)
        r2 = make_engine(shared)
        wire = grown(chain, 4).encode()
        r1.process_incoming_proposal("s", Proposal.decode(wire), NOW + 20)
        r2.process_incoming_proposal("s", Proposal.decode(wire), NOW + 20)
        a = r1.export_session("s", chain.proposal_id)
        b = r2.export_session("s", chain.proposal_id)
        assert [v.vote_hash for v in a.proposal.votes] == [
            v.vote_hash for v in b.proposal.votes
        ]
