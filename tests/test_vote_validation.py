"""Adversarial vote-mutation tests with the real ECDSA scheme
(reference: tests/vote_validation_tests.rs)."""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    EthereumConsensusSigner,
    build_vote,
    compute_vote_hash,
    validate_proposal,
)
from hashgraph_tpu.errors import (
    ConsensusSchemeError,
    EmptySignature,
    EmptyVoteHash,
    EmptyVoteOwner,
    InvalidVoteSignature,
    ParentHashMismatch,
    ReceivedHashMismatch,
)

from common import NOW, cast_remote_vote_and_get_proposal, make_service

SCOPE = "validation_scope"
EXPIRATION = 120


def resign_vote(vote, signer: EthereumConsensusSigner):
    """Re-hash and re-sign after tampering (reference: tests/vote_validation_tests.rs:29-41)."""
    vote.vote_hash = compute_vote_hash(vote)
    vote.signature = signer.sign(vote.signing_payload())


@pytest.fixture()
def eth_setup():
    service = make_service(scheme="ethereum")
    owner = EthereumConsensusSigner.random()
    request = CreateProposalRequest(
        name="Proposal",
        payload=b"",
        proposal_owner=owner.identity(),
        expected_voters_count=3,
        expiration_timestamp=EXPIRATION,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal_with_config(
        SCOPE, request, ConsensusConfig.gossipsub(), NOW
    )
    proposal = cast_remote_vote_and_get_proposal(
        service, SCOPE, proposal.proposal_id, True, owner
    )
    return service, proposal


def test_vote_created_with_helper_is_valid(eth_setup):
    service, proposal = eth_setup
    vote = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    service.process_incoming_vote(SCOPE, vote, NOW)


def test_invalid_signature_is_rejected(eth_setup):
    _, proposal = eth_setup
    voter = EthereumConsensusSigner.random()
    vote = build_vote(proposal, True, voter, NOW)
    wrong_signer = EthereumConsensusSigner.random()
    vote.signature = wrong_signer.sign(vote.signing_payload())

    invalid = proposal.clone()
    invalid.votes.append(vote)
    with pytest.raises(InvalidVoteSignature):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_vote_chain_rejects_bad_received_hash(eth_setup):
    _, proposal = eth_setup
    vote_one = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    voter_two = EthereumConsensusSigner.random()
    vote_two = build_vote(proposal, False, voter_two, NOW)
    vote_two.received_hash = b"\x00" * 32
    resign_vote(vote_two, voter_two)

    invalid = proposal.clone()
    invalid.votes.extend([vote_one, vote_two])
    with pytest.raises(ReceivedHashMismatch):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_rejects_empty_vote_owner(eth_setup):
    _, proposal = eth_setup
    vote = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    vote.vote_owner = b""
    invalid = proposal.clone()
    invalid.votes.append(vote)
    with pytest.raises(EmptyVoteOwner):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_rejects_empty_vote_hash(eth_setup):
    _, proposal = eth_setup
    vote = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    vote.vote_hash = b""
    invalid = proposal.clone()
    invalid.votes.append(vote)
    with pytest.raises(EmptyVoteHash):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_rejects_empty_signature(eth_setup):
    _, proposal = eth_setup
    vote = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    vote.signature = b""
    invalid = proposal.clone()
    invalid.votes.append(vote)
    with pytest.raises(EmptySignature):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_rejects_mismatched_signature_length(eth_setup):
    """Length checks live in the scheme and surface as scheme errors
    (reference: tests/vote_validation_tests.rs:301-334)."""
    _, proposal = eth_setup
    vote = build_vote(proposal, True, EthereumConsensusSigner.random(), NOW)
    vote.signature = b"\x07" * 64
    invalid = proposal.clone()
    invalid.votes.append(vote)
    with pytest.raises(ConsensusSchemeError):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)


def test_vote_chain_rejects_parent_hash_owner_mismatch(eth_setup):
    _, proposal = eth_setup
    # Build both votes off the 1-vote proposal so each received_hash links to
    # the owner's vote; then vote_two's parent points at vote_one (different
    # owner) which must fail the parent-chain check.
    base = proposal.clone()
    vote_one = build_vote(base, True, EthereumConsensusSigner.random(), NOW)
    base.votes.append(vote_one)
    voter_two = EthereumConsensusSigner.random()
    vote_two = build_vote(base, False, voter_two, NOW)
    vote_two.parent_hash = bytes(vote_one.vote_hash)
    resign_vote(vote_two, voter_two)

    invalid = proposal.clone()
    invalid.votes.extend([vote_one, vote_two])
    with pytest.raises(ParentHashMismatch):
        validate_proposal(invalid, EthereumConsensusSigner, NOW)
