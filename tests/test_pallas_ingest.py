"""Parity: Pallas VMEM-resident vote scan vs the XLA lax.scan ingest path.

Runs on CPU in interpreter mode (real lowering is exercised on TPU when the
pool enables the Pallas path). Inputs map the pool arrays 1:1 onto rows so
both kernels see identical state; outputs must match exactly.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from hashgraph_tpu.ops.decide import STATE_ACTIVE, required_votes_np
from hashgraph_tpu.ops.ingest import ingest_body, pack_grid, pack_slots
from hashgraph_tpu.ops.pallas_ingest import (
    SCALAR_COLS,
    _C_CAP,
    _C_EXPIRED,
    _C_GOSSIP,
    _C_LIVE,
    _C_N,
    _C_REQ,
    _C_STATE,
    _C_TOT,
    _C_YES,
    pallas_ingest_rows,
)


def build_case(seed, s_count=128, v_cap=16, l_depth=6):
    rng = np.random.default_rng(seed)
    n = rng.integers(1, v_cap + 1, s_count).astype(np.int32)
    threshold = rng.choice([2 / 3, 0.5, 1.0])
    req = required_votes_np(n, threshold).astype(np.int32)
    gossip = rng.random(s_count) < 0.5
    cap = np.where(gossip, 2, (2 * n.astype(np.int64) + 2) // 3).astype(np.int32)
    live = rng.random(s_count) < 0.5
    expired = rng.random(s_count) < 0.1
    state = np.full(s_count, STATE_ACTIVE, np.int32)
    yes = np.zeros(s_count, np.int32)
    tot = np.zeros(s_count, np.int32)
    # Pre-populate some sessions with an existing vote.
    pre = rng.random(s_count) < 0.3
    tot[pre] += 1
    preyes = pre & (rng.random(s_count) < 0.5)
    yes[preyes] += 1
    mask = np.zeros((s_count, v_cap), np.int32)
    vals = np.zeros((s_count, v_cap), np.int32)
    mask[pre, 0] = 1
    vals[preyes, 0] = 1

    voter = rng.integers(0, v_cap, (s_count, l_depth)).astype(np.int32)
    val = rng.random((s_count, l_depth)) < 0.5
    valid = rng.random((s_count, l_depth)) < 0.9
    grid = pack_grid(voter, val, valid)

    scal = np.zeros((s_count, SCALAR_COLS), np.int32)
    scal[:, _C_STATE] = state
    scal[:, _C_YES] = yes
    scal[:, _C_TOT] = tot
    scal[:, _C_N] = n
    scal[:, _C_REQ] = req
    scal[:, _C_CAP] = cap
    scal[:, _C_GOSSIP] = gossip
    scal[:, _C_LIVE] = live
    scal[:, _C_EXPIRED] = expired
    return dict(
        state=state, yes=yes, tot=tot, mask=mask, vals=vals,
        n=n, req=req, cap=cap, gossip=gossip, live=live, expired=expired,
        grid=grid, scal=scal,
    )


def test_pool_with_pallas_kernel_matches_default():
    """Pool-level smoke: a pallas-backed pool behaves identically on a
    mixed trace (interpret mode on CPU)."""
    from hashgraph_tpu.engine.pool import ProposalPool

    def run(use_pallas):
        rng = np.random.default_rng(3)
        pool = ProposalPool(16, 8, use_pallas=use_pallas)
        pool.allocate_batch(
            keys=[("s", i) for i in range(16)],
            n=np.full(16, 5),
            req=required_votes_np(np.full(16, 5), 2 / 3),
            cap=np.where(np.arange(16) % 2 == 0, 2, 4),
            gossip=(np.arange(16) % 2 == 0),
            liveness=np.ones(16, bool),
            expiry=np.full(16, 2_000_000_000),
            created_at=np.full(16, 1_700_000_000),
        )
        out = []
        for _ in range(3):
            slots = rng.integers(0, 16, 40).astype(np.int64)
            lanes = rng.integers(0, 8, 40).astype(np.int32)
            values = rng.random(40) < 0.5
            statuses, transitions = pool.ingest(slots, lanes, values, 1_700_000_000)
            out.append((statuses.tolist(), transitions))
        return out

    assert run(False) == run(True)


@pytest.mark.parametrize("seed", range(4))
def test_pallas_matches_xla_scan(seed):
    case = build_case(seed)
    s_count, v_cap = case["mask"].shape

    # XLA path: pool arrays == rows (identity slot mapping).
    slot_pack = pack_slots(
        np.arange(s_count, dtype=np.int32), case["expired"]
    )
    xla_out = ingest_body(
        jnp.asarray(case["state"]),
        jnp.asarray(case["yes"]),
        jnp.asarray(case["tot"]),
        jnp.asarray(case["mask"] != 0),
        jnp.asarray(case["vals"] != 0),
        jnp.asarray(case["n"]),
        jnp.asarray(case["req"]),
        jnp.asarray(case["cap"]),
        jnp.asarray(case["gossip"]),
        jnp.asarray(case["live"]),
        jnp.asarray(slot_pack),
        jnp.asarray(case["grid"]),
    )
    x_state, x_yes, x_tot, x_mask, x_vals, x_out = map(np.asarray, xla_out)

    p_scal, p_mask, p_vals, p_status = map(
        np.asarray,
        pallas_ingest_rows(
            jnp.asarray(case["scal"]),
            jnp.asarray(case["mask"]),
            jnp.asarray(case["vals"]),
            jnp.asarray(case["grid"]),
            block=64,
            interpret=True,
        ),
    )

    np.testing.assert_array_equal(p_scal[:, _C_STATE], x_state)
    np.testing.assert_array_equal(p_scal[:, _C_YES], x_yes)
    np.testing.assert_array_equal(p_scal[:, _C_TOT], x_tot)
    np.testing.assert_array_equal(p_mask != 0, x_mask)
    np.testing.assert_array_equal(p_vals != 0, x_vals)
    np.testing.assert_array_equal(p_status, x_out[:, :-1].astype(np.int32))
