"""Tiered session lifecycle: demote / demand-page / GC.

Two pillars:

1. **Transparency** — a tiered engine is observably identical to an
   untier'd twin fed the same traffic: statuses, results, fingerprints,
   stats, and health scorecards (typed miss statuses nowhere). Unit
   cases pin each demand-page surface; a hypothesis property drives a
   random create/vote/decide/idle/late-vote script through both twins
   with demotions sprinkled arbitrarily into the tiered one.

2. **Policy** — the per-scope TTL knobs (``demote_after`` /
   ``evict_decided_after``), the sweep hook riding
   ``sweep_timeouts``, pinned-scope exclusions, per-scope-cap
   equivalence (demoted sessions still count and evict), and the spill
   accounting in ``occupancy()`` + the shared fleet rollup.
"""

import pytest

from hashgraph_tpu import (
    ConsensusFailed,
    CreateProposalRequest,
    ScopeConfig,
    SessionNotFound,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.obs.health import HealthMonitor
from hashgraph_tpu.sync import state_fingerprint

from common import NOW

import numpy as np

SIGNERS = [StubConsensusSigner(bytes([i + 1]) * 20) for i in range(4)]


def _engine(**kw) -> TpuConsensusEngine:
    kw.setdefault("capacity", 64)
    kw.setdefault("voter_capacity", 8)
    kw.setdefault("health_monitor", HealthMonitor())
    return TpuConsensusEngine(StubConsensusSigner(b"\x42" * 20), **kw)


def _request(n=3, name="prop", exp=50):
    return CreateProposalRequest(
        name=name,
        payload=b"payload",
        proposal_owner=b"owner",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=True,
    )


def _author_proposal(n=3, name="prop", exp=50, now=NOW):
    """Mint a proposal (with a real pid) on a throwaway engine so twins
    can ingest identical bytes."""
    maker = _engine()
    return maker.create_proposal("author", _request(n, name, exp), now)


def _decide(engine, scope, proposal, votes=None):
    """Drive a proposal to YES with chained signed votes; returns the
    votes used (build once, reuse on a twin)."""
    if votes is None:
        votes = []
        chain = proposal.clone()
        for i in range(proposal.expected_voters_count):
            vote = build_vote(chain, True, SIGNERS[i], NOW + 1)
            chain.votes.append(vote)
            votes.append(vote)
    statuses = engine.ingest_votes(
        [(scope, v) for v in votes], NOW + 1
    )
    assert all(
        s in (int(StatusCode.OK), int(StatusCode.ALREADY_REACHED))
        for s in statuses
    )
    return votes


class TestDemotePromote:
    def test_fingerprint_invariant_across_demote_promote(self):
        engine = _engine()
        proposal = _author_proposal()
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        fp0 = state_fingerprint(engine)
        assert engine.demote_session("s", proposal.proposal_id) is True
        assert engine.demote_session("s", proposal.proposal_id) is False
        assert state_fingerprint(engine) == fp0, "demotion changed state"
        # Point read pages it back in.
        assert engine.get_consensus_result("s", proposal.proposal_id) is True
        assert engine.occupancy()["tier_sessions"] == 0
        assert state_fingerprint(engine) == fp0, "promotion changed state"

    def test_demoted_item_bytes_equal_snapshot_codec(self):
        """The stored tier bytes ARE the PR-8 snapshot item for the
        session — including for the bulk field-direct encode path."""
        from hashgraph_tpu.sync.snapshot import encode_session_item

        engine = _engine()
        proposal = _author_proposal()
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        expected = encode_session_item(
            "s", engine.export_session("s", proposal.proposal_id)
        )
        engine.demote_session("s", proposal.proposal_id)
        entry = engine._tier["s"][proposal.proposal_id]
        assert entry.item == expected

    def test_columnar_tally_session_roundtrip(self):
        """A session decided through columnar tallies (no Vote objects)
        demotes via the field-direct fast path and round-trips."""
        engine = _engine()
        proposal = _author_proposal(n=2)
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        gids = np.array(
            [engine.voter_gid(s.identity()) for s in SIGNERS[:2]], np.int64
        )
        pid = proposal.proposal_id
        statuses = engine.ingest_columnar(
            "s",
            np.array([pid, pid], np.int64),
            gids,
            np.array([True, True]),
            NOW + 1,
        )
        assert list(statuses) == [0, 0]
        fp0 = state_fingerprint(engine)
        engine.demote_session("s", pid)
        assert state_fingerprint(engine) == fp0
        session = engine.export_session("s", pid)  # promotes
        assert session.state.is_reached and session.state.result is True
        assert len(session.tallies) == 2
        assert state_fingerprint(engine) == fp0

    def test_host_spilled_session_demotes(self):
        """A session the pool cannot hold (host-spilled) demotes and
        promotes through the same tier."""
        engine = _engine(voter_capacity=2)
        proposal = _author_proposal(n=3)  # 3 voters > 2 lanes -> spill
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        assert engine.occupancy()["host_spilled"] == 1
        fp0 = state_fingerprint(engine)
        engine.demote_session("s", proposal.proposal_id)
        assert engine.occupancy()["host_spilled"] == 0
        assert state_fingerprint(engine) == fp0
        assert engine.get_consensus_result("s", proposal.proposal_id) is None
        assert engine.occupancy()["host_spilled"] == 1

    def test_unknown_session_raises(self):
        engine = _engine()
        with pytest.raises(SessionNotFound):
            engine.demote_session("s", 12345)


class TestDemandPaging:
    def _demoted_active(self, engine, n=3, exp=50):
        proposal = _author_proposal(n=n, exp=exp)
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        engine.demote_session("s", proposal.proposal_id)
        return proposal

    def test_late_vote_promotes_and_applies(self):
        engine = _engine()
        proposal = self._demoted_active(engine)
        vote = build_vote(proposal, True, SIGNERS[0], NOW + 1)
        statuses = engine.ingest_votes([("s", vote)], NOW + 1)
        assert list(statuses) == [int(StatusCode.OK)]
        assert engine.occupancy()["tier_sessions"] == 0
        assert engine.occupancy()["tier_promotions_total"] == 1

    def test_columnar_late_vote_promotes(self):
        engine = _engine()
        proposal = self._demoted_active(engine, n=2)
        gid = engine.voter_gid(SIGNERS[0].identity())
        statuses = engine.ingest_columnar(
            "s",
            np.array([proposal.proposal_id], np.int64),
            np.array([gid], np.int64),
            np.array([True]),
            NOW + 1,
        )
        assert list(statuses) == [int(StatusCode.OK)]
        assert engine.occupancy()["tier_sessions"] == 0

    def test_explain_and_proposal_reads_promote(self):
        engine = _engine()
        proposal = self._demoted_active(engine)
        out = engine.explain_decision("s", proposal.proposal_id)
        assert out["status"] == "active"
        assert engine.occupancy()["tier_sessions"] == 0
        engine.demote_session("s", proposal.proposal_id)
        assert (
            engine.get_proposal("s", proposal.proposal_id).proposal_id
            == proposal.proposal_id
        )

    def test_deliver_extension_promotes(self):
        engine = _engine()
        proposal = self._demoted_active(engine)
        extended = proposal.clone()
        extended.votes.append(build_vote(extended, True, SIGNERS[0], NOW + 1))
        status = engine.deliver_proposal("s", extended, NOW + 1)
        assert status == int(StatusCode.OK)
        session = engine.export_session("s", proposal.proposal_id)
        assert len(session.votes) == 1

    def test_strict_redelivery_rejects_without_promoting(self):
        from hashgraph_tpu.errors import ProposalAlreadyExist

        engine = _engine()
        proposal = self._demoted_active(engine)
        with pytest.raises(ProposalAlreadyExist):
            engine.process_incoming_proposal("s", proposal.clone(), NOW + 1)
        statuses = engine.ingest_proposals([("s", proposal.clone())], NOW + 1)
        assert statuses == [int(StatusCode.PROPOSAL_ALREADY_EXIST)]
        # The no-redelivery contract settles without paging anything in.
        assert engine.occupancy()["tier_sessions"] == 1

    def test_timeout_on_demoted_session(self):
        engine = _engine()
        proposal = self._demoted_active(engine)
        vote = build_vote(proposal, True, SIGNERS[0], NOW + 1)
        engine.ingest_votes([("s", vote)], NOW + 1)
        engine.demote_session("s", proposal.proposal_id)
        result = engine.handle_consensus_timeout(
            "s", proposal.proposal_id, NOW + 100
        )
        assert result is True  # liveness YES at timeout with one YES vote

    def test_sweep_fires_timeouts_for_demoted_sessions(self):
        engine = _engine()
        proposal = self._demoted_active(engine, exp=10)
        vote = build_vote(proposal, True, SIGNERS[0], NOW + 1)
        engine.ingest_votes([("s", vote)], NOW + 1)
        engine.demote_session("s", proposal.proposal_id)
        swept = engine.sweep_timeouts(NOW + 11)
        assert ("s", proposal.proposal_id, True) in swept

    def test_enumeration_reads_through_without_promoting(self):
        engine = _engine()
        active = self._demoted_active(engine, n=3)
        decided = _author_proposal(n=2, name="decided")
        engine.process_incoming_proposal("s", decided.clone(), NOW)
        _decide(engine, "s", decided)
        engine.demote_session("s", decided.proposal_id)
        stats = engine.get_scope_stats("s")
        assert stats.total_sessions == 2
        assert stats.active_sessions == 1
        assert stats.consensus_reached == 1
        actives = engine.get_active_proposals("s")
        assert [p.proposal_id for p in actives] == [active.proposal_id]
        reached = engine.get_reached_proposals("s")
        assert [(p.proposal_id, r) for p, r in reached] == [
            (decided.proposal_id, True)
        ]
        keys = set(engine.session_keys())
        assert keys == {("s", active.proposal_id), ("s", decided.proposal_id)}
        # All of the above read THROUGH the tier.
        assert engine.occupancy()["tier_sessions"] == 2


class TestLifecyclePolicy:
    def _tiered_scope(self, engine, demote=5.0, evict=None):
        engine.set_scope_config(
            "s", ScopeConfig(demote_after=demote, evict_decided_after=evict)
        )

    def test_ttl_demotes_idle_then_gc(self):
        engine = _engine()
        self._tiered_scope(engine, demote=5.0, evict=20.0)
        proposal = _author_proposal(n=2, name="x")
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        out = engine.lifecycle_sweep(NOW + 3)
        assert out == {"demoted": 0, "gc_live": 0, "gc_tier": 0}
        out = engine.lifecycle_sweep(NOW + 7)
        assert out["demoted"] == 1
        assert engine.occupancy()["tier_sessions"] == 1
        out = engine.lifecycle_sweep(NOW + 30)
        assert out["gc_tier"] == 1
        assert engine.occupancy()["tier_sessions"] == 0
        with pytest.raises(SessionNotFound):
            engine.get_consensus_result("s", proposal.proposal_id)

    def test_gc_live_without_demotion_window(self):
        engine = _engine()
        self._tiered_scope(engine, demote=None, evict=5.0)
        proposal = _author_proposal(n=2, name="y")
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        out = engine.lifecycle_sweep(NOW + 10)
        assert out["gc_live"] == 1
        assert engine.occupancy()["tier_gc_total"] == 1

    def test_active_sessions_never_gc(self):
        engine = _engine()
        self._tiered_scope(engine, demote=2.0, evict=4.0)
        proposal = _author_proposal(n=3, name="z", exp=1000)
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        engine.lifecycle_sweep(NOW + 100)
        occ = engine.occupancy()
        assert occ["tier_sessions"] == 1  # demoted, NOT collected
        assert occ["tier_gc_total"] == 0

    def test_pinned_scope_excluded(self):
        engine = _engine()
        self._tiered_scope(engine, demote=1.0, evict=2.0)
        proposal = _author_proposal(n=2, name="pin")
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        engine.pin_scope("s")
        out = engine.lifecycle_sweep(NOW + 100)
        assert out == {"demoted": 0, "gc_live": 0, "gc_tier": 0}
        engine.unpin_scope("s")
        out = engine.lifecycle_sweep(NOW + 100)
        assert out["gc_live"] == 1

    def test_sweep_timeouts_runs_lifecycle(self):
        engine = _engine()
        self._tiered_scope(engine, demote=5.0)
        proposal = _author_proposal(n=2, name="sw")
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        engine.sweep_timeouts(NOW + 7)
        assert engine.occupancy()["tier_sessions"] == 1

    def test_promotion_preserves_idle_clock(self):
        """Demote -> promote -> the session demotes again at the SAME
        TTL point it would have without the round-trip."""
        engine = _engine()
        self._tiered_scope(engine, demote=10.0)
        proposal = _author_proposal(n=2, name="clock")
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)  # last activity NOW + 1
        engine.lifecycle_sweep(NOW + 12)
        assert engine.occupancy()["tier_sessions"] == 1
        assert engine.get_consensus_result("s", proposal.proposal_id) is True
        out = engine.lifecycle_sweep(NOW + 13)
        assert out["demoted"] == 1  # still idle since NOW+1, re-demotes


class TestCapEquivalence:
    def test_demoted_sessions_count_against_the_scope_cap(self):
        tiered = _engine(max_sessions_per_scope=3)
        plain = _engine(max_sessions_per_scope=3)
        proposals = [
            _author_proposal(n=2, name=f"c{i}") for i in range(5)
        ]
        for k, proposal in enumerate(proposals):
            for engine in (tiered, plain):
                engine.process_incoming_proposal(
                    "s", proposal.clone(), NOW + k
                )
            if k == 1:
                # Invisible op on the tiered twin only.
                tiered.demote_session("s", proposals[0].proposal_id)
        assert state_fingerprint(tiered) == state_fingerprint(plain)
        assert set(tiered.session_keys()) == set(plain.session_keys())
        assert len(tiered.session_keys()) == 3


class TestAccounting:
    def test_occupancy_tier_counters(self):
        engine = _engine()
        proposal = _author_proposal(n=2)
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        _decide(engine, "s", proposal)
        engine.demote_session("s", proposal.proposal_id)
        occ = engine.occupancy()
        assert occ["tier_sessions"] == 1
        assert occ["tier_bytes"] > 0
        assert occ["tier_demotions_total"] == 1
        assert occ["tier_promotions_total"] == 0
        engine.get_consensus_result("s", proposal.proposal_id)
        occ = engine.occupancy()
        assert (occ["tier_sessions"], occ["tier_bytes"]) == (0, 0)
        assert occ["tier_promotions_total"] == 1

    def test_shared_rollup_carries_tier_keys(self):
        from hashgraph_tpu.parallel.rollup import (
            OCCUPANCY_SUM_KEYS,
            aggregate_occupancy,
        )

        engine = _engine()
        proposal = _author_proposal(n=2)
        engine.process_incoming_proposal("s", proposal.clone(), NOW)
        engine.demote_session("s", proposal.proposal_id)
        entry = engine.occupancy()
        for key in OCCUPANCY_SUM_KEYS:
            assert key in entry, f"engine occupancy missing {key}"
        total = aggregate_occupancy(
            [entry, {"recovering": True}, {"migrating": True}]
        )
        assert total["tier_sessions"] == 1
        assert total["unavailable_shards"] == 2

    def test_tier_metric_families_installed(self):
        from hashgraph_tpu.obs import (
            TIER_BYTES,
            TIER_DEMOTED_SESSIONS,
            TIER_DEMOTIONS_TOTAL,
            TIER_GC_TOTAL,
            TIER_PROMOTIONS_TOTAL,
            registry,
        )

        text = registry.render_prometheus()
        for family in (
            TIER_DEMOTED_SESSIONS,
            TIER_BYTES,
            TIER_DEMOTIONS_TOTAL,
            TIER_PROMOTIONS_TOTAL,
            TIER_GC_TOTAL,
        ):
            assert family in text


# ── Decision-identity: tiered twin vs untier'd oracle ──────────────────
#
# The script runner is shared with tests/test_property_tiering.py (the
# hypothesis-driven search over the same op space); the seeded trials
# below always run, external-fuzzer-free (the test_wal_recovery pattern).


def run_identity_script(script):
    """Random create/vote/decide/idle/late-vote script through a tiered
    engine and an untier'd twin: identical statuses, results,
    fingerprints, and health scorecards — demotions are invisible."""
    tiered = _engine(max_sessions_per_scope=5)
    plain = _engine(max_sessions_per_scope=5)
    sessions = []  # (scope, pid, chain proposal mirror)
    clock = NOW
    n_created = 0
    for op in script:
        kind = op[0]
        if kind == "create":
            n = op[1]
            proposal = _author_proposal(n=n, name=f"p{n_created}", now=clock)
            n_created += 1
            outcomes = []
            for engine in (tiered, plain):
                try:
                    engine.process_incoming_proposal(
                        "s", proposal.clone(), clock
                    )
                    outcomes.append(None)
                except Exception as exc:  # noqa: BLE001 — compared by type
                    outcomes.append(type(exc))
            assert outcomes[0] == outcomes[1]
            if outcomes[0] is None:
                sessions.append(("s", proposal.proposal_id, proposal.clone()))
        elif kind == "vote":
            if not sessions:
                continue
            _, pid, chain = sessions[op[1] % len(sessions)]
            vote = build_vote(chain, op[3], SIGNERS[op[2]], clock)
            st_t = tiered.ingest_votes([("s", vote)], clock)
            st_p = plain.ingest_votes([("s", vote)], clock)
            assert list(st_t) == list(st_p)
            if int(st_p[0]) == int(StatusCode.OK):
                chain.votes.append(vote.clone())
        elif kind == "timeout":
            if not sessions:
                continue
            _, pid, _ = sessions[op[1] % len(sessions)]
            out_t = out_p = err_t = err_p = None
            try:
                out_t = tiered.handle_consensus_timeout("s", pid, clock)
            except Exception as exc:  # noqa: BLE001 — compared by type
                err_t = type(exc)
            try:
                out_p = plain.handle_consensus_timeout("s", pid, clock)
            except Exception as exc:  # noqa: BLE001
                err_p = type(exc)
            assert (out_t, err_t) == (out_p, err_p)
        elif kind == "sweep":
            clock += op[1]
            swept_t = tiered.sweep_timeouts(clock)
            swept_p = plain.sweep_timeouts(clock)
            assert sorted(swept_t) == sorted(swept_p)
        elif kind == "demote":
            if not sessions:
                continue
            _, pid, _ = sessions[op[1] % len(sessions)]
            try:
                tiered.demote_session("s", pid)
            except SessionNotFound:
                pass  # evicted on BOTH twins by the scope cap
        elif kind == "demote_all":
            for _, pid, _ in sessions:
                try:
                    tiered.demote_session("s", pid)
                except SessionNotFound:
                    pass
    # Terminal equivalence: every read surface agrees.
    assert state_fingerprint(tiered) == state_fingerprint(plain)
    assert set(tiered.session_keys()) == set(plain.session_keys())
    stats_t, stats_p = (
        engine.get_scope_stats("s") for engine in (tiered, plain)
    )
    assert (
        stats_t.total_sessions,
        stats_t.active_sessions,
        stats_t.failed_sessions,
        stats_t.consensus_reached,
    ) == (
        stats_p.total_sessions,
        stats_p.active_sessions,
        stats_p.failed_sessions,
        stats_p.consensus_reached,
    )
    for scope, pid, _ in sessions:
        res_t = res_p = err_t = err_p = None
        try:
            res_t = tiered.get_consensus_result(scope, pid)
        except (SessionNotFound, ConsensusFailed) as exc:
            err_t = type(exc)
        try:
            res_p = plain.get_consensus_result(scope, pid)
        except (SessionNotFound, ConsensusFailed) as exc:
            err_p = type(exc)
        assert (res_t, err_t) == (res_p, err_p)
    # Health scorecards: same peers, same counters.
    peers_t = tiered.health.snapshot()["peers"]
    peers_p = plain.health.snapshot()["peers"]
    assert peers_t == peers_p


def _random_script(rng, n_ops):
    ops = []
    for _ in range(n_ops):
        roll = rng.random()
        if roll < 0.25:
            ops.append(("create", rng.randint(1, 4)))
        elif roll < 0.55:
            ops.append(
                (
                    "vote",
                    rng.randrange(8),
                    rng.randrange(4),
                    rng.random() < 0.6,
                )
            )
        elif roll < 0.65:
            ops.append(("timeout", rng.randrange(8)))
        elif roll < 0.78:
            ops.append(("sweep", rng.randint(1, 30)))
        elif roll < 0.92:
            ops.append(("demote", rng.randrange(8)))
        else:
            ops.append(("demote_all",))
    return ops


def test_tiered_untiered_decision_identity_seeded():
    import random

    for seed in range(12):
        rng = random.Random(1000 + seed)
        run_identity_script(_random_script(rng, rng.randint(5, 20)))
