"""Gossip fabric: negotiation interop, pipelining, coalescing,
backpressure, anti-entropy, and catch-up escalation.

Covers the ISSUE-9 acceptance surface:
- old-client<->new-server AND new-client<->old-server HELLO interop;
- concurrent pipelined stress (many in-flight correlation ids,
  out-of-order completion, connection drop failing all pending futures
  with a typed error);
- bounded send queues + shed-to-anti-entropy under a stalled peer;
- cross-peer fingerprint convergence through sampled fan-out + repair;
- far-behind-peer escalation to the state-sync CatchUpClient.
"""

import os
import socket
import struct
import threading
import time

import pytest

from hashgraph_tpu import build_vote
from hashgraph_tpu.bridge import (
    BridgeClient,
    BridgeConnectionLost,
    BridgeError,
    BridgeServer,
    PipelinedBridgeClient,
)
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.errors import StatusCode
from hashgraph_tpu.gossip import (
    ChannelBusy,
    GossipNode,
    GossipTransport,
    VoteCoalescer,
)
from hashgraph_tpu.signing.stub import StubConsensusSigner
from hashgraph_tpu.sync import state_fingerprint
from hashgraph_tpu.wire import Proposal

NOW = 1_700_000_000


@pytest.fixture()
def server():
    with BridgeServer(
        capacity=64, voter_capacity=12, signer_factory=StubConsensusSigner
    ) as srv:
        yield srv


def add_stub_peer(srv):
    with BridgeClient(*srv.address) as cl:
        return cl.add_peer(os.urandom(32))[0]


def make_chain(client, peer, scope, n_votes, expected=None):
    """Create a proposal via the bridge and build a chained stub vote
    list against it; returns (pid, proposal_bytes, votes_wire)."""
    signers = [StubConsensusSigner(os.urandom(20)) for _ in range(n_votes)]
    pid, blob = client.create_proposal(
        peer, scope, NOW, "p", b"", expected or (n_votes + 1), 3_600
    )
    proposal = Proposal.decode(blob)
    votes = []
    for signer in signers:
        vote = build_vote(proposal, True, signer, NOW + 1)
        proposal.votes.append(vote)
        votes.append(vote.encode())
    return pid, blob, votes


# ── Wire codecs ────────────────────────────────────────────────────────


class TestWireCodecs:
    def test_encode_frame_layout_unchanged(self):
        """The struct-compiled encoder emits byte-identical frames to the
        original `u32 length | u8 lead | payload` layout."""
        payload = b"\x01\x02payload"
        frame = P.encode_frame(7, payload)
        assert frame == struct.pack("<I", 1 + len(payload)) + b"\x07" + payload
        assert P.encode_frame(0) == struct.pack("<I", 1) + b"\x00"

    def test_tagged_frame_roundtrip(self):
        frame = P.encode_tagged_frame(9, 0xDEADBEEF, b"xy")
        lead, corr, cursor = P.parse_frame(frame[4:], tagged=True)
        assert (lead, corr) == (9, 0xDEADBEEF)
        assert cursor.raw(2) == b"xy" and cursor.done()

    def test_vote_batch_roundtrip_preserves_group_and_vote_order(self):
        groups = [
            (3, "scope-a", [b"v1", b"longer-vote-2", b""]),
            (1, "scope-b", []),
            (3, "scope-c", [b"v3"]),
        ]
        now, back = P.decode_vote_batch(
            P.Cursor(P.encode_vote_batch(42, groups))
        )
        assert now == 42 and back == groups

    def test_cursor_truncation_still_raises_value_error(self):
        cursor = P.Cursor(b"\x01\x02")
        with pytest.raises(ValueError):
            cursor.u32()
        with pytest.raises(ValueError):
            P.Cursor(b"\x05").string()


# ── HELLO negotiation + interop ────────────────────────────────────────


class _FakeOldServer:
    """A minimal pre-HELLO bridge: answers PING in the old framing and
    UNKNOWN_OPCODE for anything else — the exact behavior of a server
    built before feature negotiation existed."""

    def __init__(self):
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.address = self._listener.getsockname()[:2]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            conn, _ = self._listener.accept()
        except OSError:
            return
        with conn:
            while True:
                try:
                    opcode, _cursor = P.read_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                if opcode == P.OP_PING:
                    conn.sendall(
                        P.encode_frame(P.STATUS_OK, P.u32(P.PROTOCOL_VERSION))
                    )
                else:
                    conn.sendall(P.encode_frame(P.STATUS_UNKNOWN_OPCODE))

    def close(self):
        try:
            self._listener.close()
        except OSError:
            pass


class TestNegotiation:
    def test_old_client_against_new_server(self, server):
        """A client that never sends HELLO gets exactly the old wire."""
        with BridgeClient(*server.address) as cl:
            peer = cl.add_peer(os.urandom(32))[0]
            pid, _ = cl.create_proposal(peer, "old", NOW, "p", b"", 3, 600)
            assert cl.get_result(peer, "old", pid) is None
            assert cl.poll_events(peer) == []

    def test_serial_hello_negotiates_non_pipelined_features(self, server):
        with BridgeClient(*server.address) as cl:
            granted = cl.hello()
            assert granted == P.SUPPORTED_FEATURES & ~P.FEATURE_PIPELINING
            # the connection stays serial and fully usable
            assert cl.ping() == P.PROTOCOL_VERSION
            with pytest.raises(ValueError):
                cl.hello(P.FEATURE_PIPELINING)

    def test_new_client_against_new_server_pipelines(self, server):
        with PipelinedBridgeClient(*server.address) as pc:
            assert pc.pipelined
            assert pc.features == P.SUPPORTED_FEATURES
            assert pc.ping() == P.PROTOCOL_VERSION

    def test_new_client_against_old_server_falls_back_serial(self):
        fake = _FakeOldServer()
        try:
            with PipelinedBridgeClient(*fake.address) as pc:
                assert not pc.pipelined
                assert pc.features == 0
                # Calls still work, one frame at a time.
                assert pc.ping() == P.PROTOCOL_VERSION
                future = pc.submit(P.OP_PING)
                assert future.done()  # serial fallback resolves inline
        finally:
            fake.close()

    def test_transport_against_old_server_falls_back_fifo(self):
        fake = _FakeOldServer()
        try:
            with GossipTransport() as transport:
                channel = transport.connect("old", *fake.address)
                assert not channel.pipelined
                assert channel.max_inflight == 1
                future = transport.request("old", P.OP_PING)
                assert future.result(5).u32() == P.PROTOCOL_VERSION
        finally:
            fake.close()

    def test_serial_and_pipelined_connections_coexist(self, server):
        """Negotiation is per-connection: an upgraded connection never
        changes what a plain client sees on its own socket."""
        with PipelinedBridgeClient(*server.address) as pc:
            with BridgeClient(*server.address) as cl:
                assert pc.pipelined
                assert cl.ping() == P.PROTOCOL_VERSION
                assert pc.ping() == P.PROTOCOL_VERSION


# ── Pipelined stress ───────────────────────────────────────────────────


class TestPipelinedStress:
    def test_many_inflight_correlation_ids(self, server):
        peer = add_stub_peer(server)
        with PipelinedBridgeClient(*server.address) as pc:
            pid, blob, votes = make_chain(pc, peer, "stress", 8)
            futures = [pc.ping_async() for _ in range(100)]
            vote_futures = [
                pc.process_votes_async(peer, "stress", votes[i : i + 2], NOW + 1)
                for i in range(0, len(votes), 2)
            ]
            more_pings = [pc.ping_async() for _ in range(100)]
            assert all(f.result(10) == P.PROTOCOL_VERSION for f in futures)
            assert all(f.result(10) == P.PROTOCOL_VERSION for f in more_pings)
            statuses = [code for f in vote_futures for code in f.result(10)]
            assert all(
                code in (int(StatusCode.OK), int(StatusCode.ALREADY_REACHED))
                for code in statuses
            )

    def test_out_of_order_completion(self, server):
        """A slow mutating opcode must not block a read-only one: the
        ping submitted AFTER the stalled vote frame completes first, and
        correlation matching still routes both results correctly."""
        peer = add_stub_peer(server)
        engine = server.peer_engine(peer)
        release = threading.Event()
        original = engine.ingest_votes  # OP_PROCESS_VOTES lands here

        def stalled(*args, **kwargs):
            release.wait(timeout=60)
            return original(*args, **kwargs)

        engine.ingest_votes = stalled
        try:
            with PipelinedBridgeClient(*server.address) as pc:
                _pid, _blob, votes = make_chain(pc, peer, "ooo", 2)
                vote_future = pc.process_votes_async(peer, "ooo", votes, NOW + 1)
                ping_future = pc.ping_async()
                assert ping_future.result(30) == P.PROTOCOL_VERSION
                assert not vote_future.done()  # still stalled
                release.set()
                assert len(vote_future.result(30)) == len(votes)
        finally:
            engine.ingest_votes = original
            release.set()

    def test_connection_drop_fails_all_pending_futures(self, server):
        peer = add_stub_peer(server)
        engine = server.peer_engine(peer)
        release = threading.Event()
        original = engine.ingest_votes  # OP_PROCESS_VOTES lands here

        def stalled(*args, **kwargs):
            release.wait(timeout=60)
            return original(*args, **kwargs)

        engine.ingest_votes = stalled
        try:
            pc = PipelinedBridgeClient(*server.address)
            _pid, _blob, votes = make_chain(pc, peer, "drop", 2)
            futures = [
                pc.process_votes_async(peer, "drop", votes, NOW + 1)
                for _ in range(3)
            ]
            pc.close()  # connection dies with the frames in flight
            for future in futures:
                with pytest.raises(BridgeConnectionLost):
                    future.result(10)
        finally:
            engine.ingest_votes = original
            release.set()

    def test_submit_after_close_is_typed(self, server):
        pc = PipelinedBridgeClient(*server.address)
        pc.close()
        with pytest.raises(BridgeConnectionLost):
            pc.ping_async().result(5)


# ── New opcodes ────────────────────────────────────────────────────────


class TestVoteBatchOpcode:
    def test_coalesced_frame_lands_on_all_named_peers(self, server):
        peer_a = add_stub_peer(server)
        peer_b = add_stub_peer(server)
        with PipelinedBridgeClient(*server.address) as pc:
            pid, blob, votes = make_chain(pc, peer_a, "vb", 4)
            pc.process_proposal(peer_b, "vb", blob, NOW)
            statuses = pc.vote_batch_async(
                NOW + 1,
                [(peer_a, "vb", votes[:2]),
                 (peer_b, "vb", votes),
                 (peer_a, "vb", votes[2:])],
            ).result(10)
            assert len(statuses) == 8
            assert all(code == int(StatusCode.OK) for code in statuses)
            assert (
                pc.call(P.OP_STATE_FINGERPRINT, P.u32(peer_a)).string()
                == pc.call(P.OP_STATE_FINGERPRINT, P.u32(peer_b)).string()
            )

    def test_bad_rows_do_not_poison_the_frame(self, server):
        peer = add_stub_peer(server)
        with PipelinedBridgeClient(*server.address) as pc:
            _pid, _blob, votes = make_chain(pc, peer, "vb2", 2)
            statuses = pc.vote_batch_async(
                NOW + 1,
                [(peer, "vb2", [votes[0], b"\xff\xffgarbage"]),
                 (9999, "vb2", [votes[1]])],
            ).result(10)
            assert statuses[0] == int(StatusCode.OK)
            assert statuses[1] == P.STATUS_BAD_REQUEST
            assert statuses[2] == P.STATUS_UNKNOWN_PEER


class TestDeliverOpcode:
    def test_create_extend_redeliver_over_the_wire(self, server):
        source = add_stub_peer(server)
        target = add_stub_peer(server)
        with BridgeClient(*server.address) as cl:
            _pid, blob, votes = make_chain(cl, source, "dl", 4)
            cl.process_votes(source, "dl", votes[:2], NOW + 1)
            grown = cl.get_proposal(source, "dl", Proposal.decode(blob).proposal_id)
            # unknown session -> created whole
            assert cl.deliver_proposals(target, [("dl", grown)], NOW) == [
                int(StatusCode.OK)
            ]
            # identical chain -> crypto-free settle
            assert cl.deliver_proposals(target, [("dl", grown)], NOW) == [
                int(StatusCode.PROPOSAL_ALREADY_EXIST)
            ]
            # extension -> suffix applied
            cl.process_votes(source, "dl", votes[2:], NOW + 1)
            pid = Proposal.decode(blob).proposal_id
            extended = cl.get_proposal(source, "dl", pid)
            assert cl.deliver_proposals(target, [("dl", extended)], NOW + 1) == [
                int(StatusCode.OK)
            ]
            assert cl.state_fingerprint(source) == cl.state_fingerprint(target)

    def test_undecodable_item_marks_only_its_row(self, server):
        target = add_stub_peer(server)
        with BridgeClient(*server.address) as cl:
            source = add_stub_peer(server)
            _pid, blob, _votes = make_chain(cl, source, "dlx", 2)
            statuses = cl.deliver_proposals(
                target, [("dlx", b"\x00garbage"), ("dlx", blob)], NOW
            )
            assert statuses == [P.STATUS_BAD_REQUEST, int(StatusCode.OK)]


class TestPollEventsBound:
    def test_bound_and_more_flag(self, server):
        with BridgeClient(*server.address) as cl:
            peers = [cl.add_peer(os.urandom(32))[0] for _ in range(3)]
            for scope in ("e1", "e2"):
                pid, _ = cl.create_proposal(peers[0], scope, NOW, "p", b"", 3, 600)
                cl.cast_vote(peers[0], scope, pid, True, NOW + 1)
                proposal = cl.get_proposal(peers[0], scope, pid)
                for peer in peers[1:]:
                    cl.process_proposal(peer, scope, proposal, NOW + 2)
                for i, voter in enumerate(peers[1:], start=1):
                    vote = cl.cast_vote(voter, scope, pid, True, NOW + 2 + i)
                    for other in peers:
                        if other != voter:
                            cl.process_vote(other, scope, vote, NOW + 3 + i)
            first, more = cl.poll_events(peers[0], max_events=1)
            assert len(first) == 1 and more is True
            rest, more = cl.poll_events(peers[0], max_events=100)
            assert len(rest) >= 1 and more is False
            # unbounded request on the same server: old wire shape
            assert cl.poll_events(peers[0]) == []


# ── Coalescer ──────────────────────────────────────────────────────────


class TestVoteCoalescer:
    def test_flush_votes_threshold_seals_the_window(self):
        coalescer = VoteCoalescer(flush_votes=3, flush_interval=999)
        assert coalescer.add("p", 1, "s", b"v1", NOW) is None
        assert coalescer.add("p", 1, "t", b"v2", NOW + 5) is None
        ready = coalescer.add("p", 1, "s", b"v3", NOW)
        assert ready is not None
        payload, meta = ready
        assert meta == [(1, "s", 2), (1, "t", 1)]
        # The payload is a SEGMENT LIST (send-side zero-copy): the tail
        # segments ARE the caller's vote bytes objects, un-copied, and
        # the joined stream is the canonical encode_vote_batch form.
        assert isinstance(payload, list)
        assert payload[1:] == [b"v1", b"v3", b"v2"]
        now, groups = P.decode_vote_batch(P.Cursor(b"".join(payload)))
        assert now == NOW + 5  # the frame carries the window's max now
        assert groups == [(1, "s", [b"v1", b"v3"]), (1, "t", [b"v2"])]
        assert coalescer.pending("p") == 0

    def test_flush_bytes_threshold(self):
        coalescer = VoteCoalescer(flush_votes=10_000, flush_bytes=8)
        assert coalescer.add("p", 1, "s", b"aaaa", NOW) is None
        assert coalescer.add("p", 1, "s", b"bbbb", NOW) is not None

    def test_interval_due_and_manual_flush(self):
        clock = [0.0]
        coalescer = VoteCoalescer(
            flush_votes=100, flush_interval=0.5, clock=lambda: clock[0]
        )
        coalescer.add("p", 1, "s", b"v", NOW)
        assert coalescer.due() == []
        clock[0] = 1.0
        assert coalescer.due() == ["p"]
        payload, meta = coalescer.flush("p")
        assert meta == [(1, "s", 1)]
        assert coalescer.flush("p") is None

    def test_windows_are_per_peer(self):
        coalescer = VoteCoalescer(flush_votes=2)
        assert coalescer.add("a", 1, "s", b"v", NOW) is None
        assert coalescer.add("b", 2, "s", b"v", NOW) is None
        assert coalescer.add("a", 1, "s", b"v", NOW) is not None
        assert coalescer.pending("b") == 1


# ── Backpressure ───────────────────────────────────────────────────────


class _StalledPeer:
    """Accepts one connection, grants HELLO, then never reads again —
    the pathological slow peer the bounded queues must survive."""

    def __init__(self):
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.address = self._listener.getsockname()[:2]
        self.conn = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            conn, _ = self._listener.accept()
        except OSError:
            return
        self.conn = conn
        try:
            opcode, _cursor = P.read_frame(conn)
            assert opcode == P.OP_HELLO
            conn.sendall(P.encode_frame(
                P.STATUS_OK,
                P.u32(P.PROTOCOL_VERSION) + P.u32(P.SUPPORTED_FEATURES),
            ))
        except (ConnectionError, OSError, ValueError):
            return
        # ... and never reads again.

    def close(self):
        try:
            self._listener.close()
        except OSError:
            pass
        if self.conn is not None:
            try:
                self.conn.close()
            except OSError:
                pass


class TestBackpressure:
    def test_stalled_peer_sheds_at_the_byte_cap(self):
        stalled = _StalledPeer()
        transport = GossipTransport(
            max_inflight=2, max_queue_bytes=16 * 1024, sndbuf=4096
        )
        try:
            channel = transport.connect("slow", *stalled.address)
            payload = b"x" * 2048
            sheds = 0
            futures = []
            for _ in range(64):
                future = transport.try_request("slow", P.OP_PING, payload)
                if future is None:
                    sheds += 1
                else:
                    futures.append(future)
            assert sheds > 0, "queue never shed under a stalled peer"
            stats = channel.stats()
            assert stats["queue_bytes"] <= 16 * 1024
            assert stats["shed_total"] == sheds
            # The stalled peer dying fails every queued/in-flight future
            # with the typed signal instead of hanging.
            stalled.close()
            for future in futures:
                with pytest.raises((BridgeConnectionLost, BridgeError)):
                    future.result(10)
        finally:
            transport.close()
            stalled.close()

    def test_request_raises_channel_busy_instead_of_shedding(self):
        stalled = _StalledPeer()
        transport = GossipTransport(
            max_inflight=1, max_queue_bytes=4096, sndbuf=4096
        )
        try:
            transport.connect("slow", *stalled.address)
            with pytest.raises(ChannelBusy):
                for _ in range(64):
                    transport.request("slow", P.OP_PING, b"y" * 1024)
        finally:
            transport.close()
            stalled.close()

    def test_frame_bigger_than_byte_cap_sends_when_queue_empty(self, server):
        """The byte cap bounds QUEUED frames; a single frame larger than
        the cap itself is admitted whenever the queue is empty —
        otherwise it could never be sent at all (shed-retry forever)."""
        transport = GossipTransport(max_queue_bytes=1024)
        try:
            transport.connect("p", *server.address)
            future = transport.try_request("p", P.OP_PING, b"z" * 8192)
            assert future is not None, "oversize frame was shed"
            assert future.result(10).u32() == P.PROTOCOL_VERSION
        finally:
            transport.close()

    def test_segment_count_past_iov_max_still_sends(self, server):
        """sendmsg takes at most IOV_MAX iovecs per call; a frame built
        from more segments than that must be written in capped passes,
        not fail the channel with EINVAL."""
        transport = GossipTransport()
        try:
            transport.connect("p", *server.address)
            segments = [b"ab"] * 3000  # > IOV_MAX (1024 on Linux)
            future = transport.try_request("p", P.OP_PING, segments)
            assert future is not None
            assert future.result(10).u32() == P.PROTOCOL_VERSION
        finally:
            transport.close()


# ── GossipNode: fan-out, repair, escalation ────────────────────────────


class TestGossipNode:
    def _mesh(self, n):
        servers, clients, peers = [], [], []
        for _ in range(n):
            srv = BridgeServer(
                capacity=64, voter_capacity=12,
                signer_factory=StubConsensusSigner,
            )
            srv.start()
            cl = BridgeClient(*srv.address)
            peers.append(cl.add_peer(os.urandom(32))[0])
            servers.append(srv)
            clients.append(cl)
        return servers, clients, peers

    def _teardown(self, servers, clients):
        for cl in clients:
            cl.close()
        for srv in servers:
            srv.stop()

    def test_reap_single_done_probe_never_drops_a_frame(self):
        """Regression: _reap used to probe ``future.done()`` twice (one
        comprehension for the harvested list, one for the remainder). A
        future resolving on the transport reader thread BETWEEN the two
        probes landed in neither list — the frame vanished unharvested
        and its acks fell out of every drain report. A future whose
        done() flips mid-reap must still be tallied exactly once."""
        from concurrent.futures import Future

        class _FlipFuture(Future):
            """done() lies False on the first probe, True after — the
            narrowest emulation of a frame completing mid-reap."""

            def __init__(self, payload):
                super().__init__()
                self.set_result(payload)
                self._probes = 0

            def done(self):
                self._probes += 1
                return self._probes > 1

        node = GossipNode("driver")
        try:
            response = P.Cursor(
                P.u32(3)
                + bytes([0, 0, int(StatusCode.ALREADY_REACHED)])
            )
            meta = [(1, "scope", 3)]
            node._outstanding.append(
                ("peerX", meta, _FlipFuture(response))
            )
            node._reap()  # the buggy version dropped the entry here
            report = node.drain()
            assert report["acked"] == 3, report
            assert report["failed_frames"] == 0
        finally:
            node.close()

    def test_fanout_delivers_to_every_peer(self):
        servers, clients, peers = self._mesh(2)
        node = GossipNode("driver", fanout=None)
        try:
            for i, srv in enumerate(servers):
                node.add_peer(f"peer{i}", *srv.address, peers[i])
            _pid, blob, votes = make_chain(clients[0], peers[0], "fan", 6)
            clients[1].process_proposal(peers[1], "fan", blob, NOW)
            pid = Proposal.decode(blob).proposal_id
            node.submit_votes("fan", pid, votes, NOW + 1, local=False)
            report = node.drain()
            assert report["acked"] == 12 and report["shed_total"] == 0
            assert (
                clients[0].state_fingerprint(peers[0])
                == clients[1].state_fingerprint(peers[1])
            )
        finally:
            node.close()
            self._teardown(servers, clients)

    def test_sampled_fanout_plus_anti_entropy_converges(self):
        servers, clients, peers = self._mesh(3)
        node = GossipNode(
            "n0", engine=servers[0].peer_engine(peers[0]), fanout=1, seed=7
        )
        try:
            for i in (1, 2):
                node.add_peer(f"peer{i}", *servers[i].address, peers[i])
            _pid, blob, votes = make_chain(clients[0], peers[0], "ae", 6)
            for i in (1, 2):
                clients[i].process_proposal(peers[i], "ae", blob, NOW)
            pid = Proposal.decode(blob).proposal_id
            node.submit_votes("ae", pid, votes, NOW + 1, local=True)
            node.drain()
            report = node.anti_entropy(NOW + 1)
            assert report["pushed_sessions"] >= 1
            fingerprints = {
                cl.state_fingerprint(peer)
                for cl, peer in zip(clients, peers)
            }
            assert len(fingerprints) == 1
            # A second round settles crypto-free as pure redelivery.
            second = node.anti_entropy(NOW + 1)
            assert second["redelivered"] == second["pushed_sessions"]
        finally:
            node.close()
            self._teardown(servers, clients)

    def test_stalled_peer_sheds_then_recovers_via_anti_entropy(self):
        servers, clients, peers = self._mesh(2)
        release = threading.Event()
        engine1 = servers[1].peer_engine(peers[1])
        original = engine1.ingest_votes_pipelined

        def stalled(*args, **kwargs):
            release.wait(timeout=30)
            return original(*args, **kwargs)

        engine1.ingest_votes_pipelined = stalled
        transport = GossipTransport(
            max_inflight=1, max_queue_bytes=2048, sndbuf=4096
        )
        node = GossipNode(
            "n0", engine=servers[0].peer_engine(peers[0]),
            transport=transport, fanout=None, flush_votes=4,
        )
        try:
            node.add_peer("peer1", *servers[1].address, peers[1])
            _pid, blob, votes = make_chain(clients[0], peers[0], "bp", 10)
            clients[1].process_proposal(peers[1], "bp", blob, NOW)
            pid = Proposal.decode(blob).proposal_id
            # Flood while the peer is stalled: the bounded queue sheds.
            node.submit_votes("bp", pid, votes, NOW + 1, local=True)
            node.flush_all()
            channel = transport.channel("peer1")
            assert channel.stats()["queue_bytes"] <= 2048
            release.set()
            report = node.drain()
            if report["shed_total"]:
                # Shed scopes are owed an anti-entropy push; the repair
                # round brings the stalled peer back to identical state.
                repair = node.anti_entropy(NOW + 1)
                assert repair["pushed_sessions"] >= 1
            assert (
                clients[0].state_fingerprint(peers[0])
                == clients[1].state_fingerprint(peers[1])
            )
        finally:
            engine1.ingest_votes_pipelined = original
            release.set()
            node.close()
            transport.close()
            self._teardown(servers, clients)

    def test_fresh_node_escalates_to_catch_up(self, tmp_path):
        """A far-behind (fresh) node with a durable peer far ahead pulls
        a snapshot+tail catch-up instead of absorbing deliver frames."""
        from hashgraph_tpu.engine import TpuConsensusEngine

        server = BridgeServer(
            capacity=64, voter_capacity=12,
            signer_factory=StubConsensusSigner,
            wal_dir=str(tmp_path / "wal"), wal_fsync="off",
        )
        server.start()
        client = BridgeClient(*server.address)
        try:
            peer = client.add_peer(os.urandom(32))[0]
            for i in range(3):
                _pid, _blob, votes = make_chain(
                    client, peer, f"hist-{i}", 4
                )
                client.process_votes(peer, f"hist-{i}", votes, NOW + 1)
            joiner = TpuConsensusEngine(
                StubConsensusSigner(b"joiner" + b"\x00" * 14),
                capacity=64, voter_capacity=12,
            )
            node = GossipNode(
                "joiner", engine=joiner, escalate_sessions=2, seed=3
            )
            try:
                node.add_peer("source", *server.address, peer)
                report = node.anti_entropy(NOW + 1)
                assert report["escalated"] is not None
                assert report["escalated"]["sessions_installed"] == 3
                assert state_fingerprint(joiner) == client.state_fingerprint(
                    peer
                )
                # The installed sessions joined the bookkeeping: the next
                # round can PUSH them (the source settles redeliveries).
                second = node.anti_entropy(NOW + 1)
                assert second["escalated"] is None
                assert second["pushed_sessions"] == 3
                assert second["redelivered"] == 3
            finally:
                node.close()
        finally:
            client.close()
            server.stop()

    def test_fanout_sample_is_sticky_per_session(self):
        """Chunks of one session must all go to the SAME sampled subset:
        interleaved fragments across different subsets would not be
        positional prefixes of the pusher's chain, so anti-entropy could
        never repair them to byte-identical state. Per-vote submits with
        fanout=1 + one repair round must still converge all peers."""
        servers, clients, peers = self._mesh(3)
        node = GossipNode(
            "sticky", engine=servers[0].peer_engine(peers[0]),
            fanout=1, seed=11, flush_votes=2,
        )
        try:
            for i in (1, 2):
                node.add_peer(f"peer{i}", *servers[i].address, peers[i])
            _pid, blob, votes = make_chain(clients[0], peers[0], "st", 6)
            for i in (1, 2):
                clients[i].process_proposal(peers[i], "st", blob, NOW)
            pid = Proposal.decode(blob).proposal_id
            for vote in votes:  # one submit per vote: worst-case chunking
                node.submit_votes("st", pid, [vote], NOW + 1)
            node.drain()
            assert len(node._session_targets[("st", pid)]) == 1  # one subset
            node.anti_entropy(NOW + 1)
            fingerprints = {
                cl.state_fingerprint(peer)
                for cl, peer in zip(clients, peers)
            }
            assert len(fingerprints) == 1
        finally:
            node.close()
            self._teardown(servers, clients)

    def test_session_bookkeeping_is_bounded(self, monkeypatch):
        """A pure driver never anti-entropy-prunes, so the session /
        sticky-sample maps must evict oldest-first at the cap instead of
        growing with every session ever submitted."""
        node = GossipNode("bounded")
        monkeypatch.setattr(node, "_MAX_TRACKED_SESSIONS", 8)
        try:
            for i in range(20):
                node.note_session(f"s{i}", i)
            assert node._tracked <= 8
            assert len(node._sessions) <= 8
            assert "s0" not in node._sessions  # oldest evicted
            assert "s19" in node._sessions  # newest kept
        finally:
            node.close()

    def test_session_rotation_covers_everything_across_rounds(self):
        """max_sessions smaller than the session count must not starve
        the tail: the per-peer cursor rotates, so successive rounds
        cover every session."""
        node = GossipNode("rot")
        try:
            for i in range(5):
                node.note_session(f"s{i}", 100 + i)
            seen = set()
            for _ in range(3):
                batch = node._session_batch("peer", max_sessions=2)
                assert len(batch) == 2
                seen.update(batch)
            assert seen == {(f"s{i}", 100 + i) for i in range(5)}
        finally:
            node.close()

    def test_outstanding_frames_are_reaped_without_drain(self):
        """A long-lived node that only pumps must not accumulate
        resolved futures; the tallies still reach the next drain()."""
        servers, clients, peers = self._mesh(1)
        node = GossipNode("reaper", fanout=None, flush_votes=2)
        try:
            node.add_peer("peer0", *servers[0].address, peers[0])
            _pid, blob, votes = make_chain(clients[0], peers[0], "reap", 10)
            pid = Proposal.decode(blob).proposal_id
            for vote in votes:
                node.submit_votes("reap", pid, [vote], NOW + 1, local=False)
            deadline = time.monotonic() + 10
            while node._outstanding and time.monotonic() < deadline:
                node.pump()
                time.sleep(0.02)
            assert not node._outstanding  # reaped, not hoarded
            report = node.drain()
            assert report["acked"] == 10  # reaped tallies not lost
        finally:
            node.close()
            self._teardown(servers, clients)

    def test_undurable_peer_skips_escalation(self):
        servers, clients, peers = self._mesh(1)
        from hashgraph_tpu.engine import TpuConsensusEngine

        joiner = TpuConsensusEngine(
            StubConsensusSigner(b"j" * 20), capacity=16, voter_capacity=8
        )
        node = GossipNode("joiner", engine=joiner, escalate_sessions=1)
        try:
            node.add_peer("source", *servers[0].address, peers[0])
            report = node.anti_entropy(NOW)
            assert report["escalated"] is None  # probe rejected, no crash
        finally:
            node.close()
            self._teardown(servers, clients)


class TestReconnectPolicy:
    """Opt-in bounded jittered auto-reconnect (satellite): a dropped
    channel heals — fresh socket, fresh HELLO — while in-flight requests
    still fail typed. Without the policy, dead channels stay dead (the
    pre-existing contract, unchanged)."""

    def test_policy_delay_is_bounded_and_jittered(self):
        from hashgraph_tpu.bridge.client import ReconnectPolicy

        policy = ReconnectPolicy(
            max_attempts=5, base_delay=0.1, max_delay=0.4, jitter=0.5
        )
        for attempt in range(10):
            d = policy.delay(attempt)
            assert 0 <= d <= 0.4
        with pytest.raises(ValueError):
            ReconnectPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            ReconnectPolicy(jitter=1.5)

    def test_gossip_transport_reconnects_after_server_restart(self):
        from hashgraph_tpu.bridge.client import ReconnectPolicy
        from hashgraph_tpu.gossip.transport import GossipTransport

        first = BridgeServer(capacity=8, voter_capacity=4)
        host, port = first.start()
        transport = GossipTransport(
            reconnect=ReconnectPolicy(
                max_attempts=40, base_delay=0.02, max_delay=0.05
            )
        )
        try:
            transport.connect("peer", host, port)
            assert transport.request("peer", P.OP_PING).result(5)
            first.stop()
            # The restarted server binds the SAME port (the crash-restart
            # shape); the transport's backoff loop re-dials + re-HELLOs.
            with BridgeServer(capacity=8, voter_capacity=4, port=port):
                deadline = time.monotonic() + 10
                healed = False
                while time.monotonic() < deadline:
                    channel = transport.channel("peer")
                    if channel is not None and channel.alive:
                        try:
                            transport.request("peer", P.OP_PING).result(5)
                            healed = True
                            break
                        except (BridgeError, ConnectionError, TimeoutError):
                            pass
                    time.sleep(0.02)
                assert healed, "channel did not heal after restart"
        finally:
            transport.close()
            first.stop()

    def test_pipelined_client_reconnects_after_server_restart(self):
        from hashgraph_tpu.bridge.client import (
            PipelinedBridgeClient,
            ReconnectPolicy,
        )

        first = BridgeServer(capacity=8, voter_capacity=4)
        host, port = first.start()
        client = PipelinedBridgeClient(
            host, port,
            reconnect=ReconnectPolicy(
                max_attempts=40, base_delay=0.02, max_delay=0.05
            ),
        )
        try:
            assert client.pipelined
            assert client.ping() == P.PROTOCOL_VERSION
            first.stop()
            with BridgeServer(capacity=8, voter_capacity=4, port=port):
                deadline = time.monotonic() + 10
                healed = False
                while time.monotonic() < deadline:
                    try:
                        if client.ping() == P.PROTOCOL_VERSION:
                            healed = True
                            break
                    except (ConnectionError, BridgeError, TimeoutError):
                        pass
                    time.sleep(0.02)
                assert healed, "client did not heal after restart"
        finally:
            client.close()
            first.stop()

    def test_without_policy_channel_stays_dead(self):
        from hashgraph_tpu.gossip.transport import GossipTransport

        server = BridgeServer(capacity=8, voter_capacity=4)
        host, port = server.start()
        transport = GossipTransport()  # no reconnect: the old contract
        try:
            transport.connect("peer", host, port)
            transport.request("peer", P.OP_PING).result(5)
            server.stop()
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline:
                channel = transport.channel("peer")
                if channel is not None and not channel.alive:
                    break
                try:
                    transport.request("peer", P.OP_PING).result(0.2)
                except Exception:
                    pass
                time.sleep(0.02)
            channel = transport.channel("peer")
            assert channel is not None and not channel.alive
            time.sleep(0.3)  # a reconnector would have re-dialed by now
            assert not transport.channel("peer").alive
        finally:
            transport.close()
