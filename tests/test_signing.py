"""Signature scheme tests: Ethereum ECDSA (with pinned interop vectors) and
the stub scheme (reference behavior: tests/custom_scheme_tests.rs,
src/signing/ethereum.rs:66-97)."""

import pytest

from hashgraph_tpu.errors import ConsensusSchemeError
from hashgraph_tpu.signing import EthereumConsensusSigner, StubConsensusSigner
from hashgraph_tpu.signing._keccak import keccak256
from hashgraph_tpu.signing.ethereum import eip191_hash


class TestKeccak:
    def test_known_vectors(self):
        assert (
            keccak256(b"").hex()
            == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
        )
        assert (
            keccak256(b"abc").hex()
            == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
        )

    def test_multiblock(self):
        # > 136-byte rate exercises the absorb loop.
        assert len(keccak256(b"x" * 500)) == 32


class TestEthereumSigner:
    def test_known_address(self):
        # secp256k1 private key 1 has a well-known Ethereum address.
        signer = EthereumConsensusSigner(1)
        assert signer.identity().hex() == "7e5f4552091a69125d5dfcb7b8c2659029395bdf"

    def test_interop_vector(self):
        # Pinned vector produced by eth_account / alloy for the same key+message;
        # byte-identity proves wire-compatible signatures with the reference.
        pk = bytes.fromhex(
            "4c0883a69102937d6231471b5dbb6204fe5129617082792ae468d01a3f362318"
        )
        msg = b"Some data"
        assert (
            eip191_hash(msg).hex()
            == "1da44b586eb0729ff70a73c326926f6ed5a25f5b056e7f47fbc6e58d86871655"
        )
        sig = EthereumConsensusSigner(pk).sign(msg)
        assert sig.hex() == (
            "b91467e570a6466aa9e9876cbcd013baba02900b8979d43fe208a4a4f339f5fd"
            "6007e74cd82e037b800186422fc2da167c747ef045e5d18a5f5d4300f8e1a029"
            "1c"
        )

    def test_sign_verify_roundtrip(self):
        signer = EthereumConsensusSigner.random()
        sig = signer.sign(b"payload")
        assert len(sig) == 65
        assert EthereumConsensusSigner.verify(signer.identity(), b"payload", sig)

    def test_wrong_identity_fails(self):
        a, b = EthereumConsensusSigner.random(), EthereumConsensusSigner.random()
        sig = a.sign(b"payload")
        assert not EthereumConsensusSigner.verify(b.identity(), b"payload", sig)

    def test_tampered_payload_fails(self):
        signer = EthereumConsensusSigner.random()
        sig = signer.sign(b"payload")
        assert not EthereumConsensusSigner.verify(signer.identity(), b"payloaX", sig)

    def test_wrong_signature_length_raises(self):
        signer = EthereumConsensusSigner.random()
        with pytest.raises(ConsensusSchemeError):
            EthereumConsensusSigner.verify(signer.identity(), b"p", b"\x00" * 64)

    def test_wrong_identity_length_raises(self):
        signer = EthereumConsensusSigner.random()
        sig = signer.sign(b"p")
        with pytest.raises(ConsensusSchemeError):
            EthereumConsensusSigner.verify(b"\x00" * 19, b"p", sig)

    def test_deterministic_signatures(self):
        signer = EthereumConsensusSigner(12345)
        assert signer.sign(b"x") == signer.sign(b"x")

    def test_invalid_private_keys_rejected(self):
        with pytest.raises(ValueError):
            EthereumConsensusSigner(0)
        with pytest.raises(ValueError):
            EthereumConsensusSigner(b"short")


class TestStubSigner:
    def test_roundtrip(self):
        s = StubConsensusSigner(b"peer-1")
        sig = s.sign(b"data")
        assert StubConsensusSigner.verify(b"peer-1", b"data", sig)
        assert not StubConsensusSigner.verify(b"peer-2", b"data", sig)
        assert not StubConsensusSigner.verify(b"peer-1", b"datb", sig)

    def test_empty_identity_rejected(self):
        with pytest.raises(ValueError):
            StubConsensusSigner(b"")
