"""Bit-exactness parity: vectorized decide kernel vs the scalar oracle.

Exhaustive sweep over small (n, tot, yes, liveness, is_timeout) space for a
spread of thresholds — every golden case from the reference's threshold tables
is contained in this grid — plus randomized large-n spot checks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hashgraph_tpu.ops import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    decide_kernel,
    decide_update,
    required_votes_np,
    timeout_update,
)
from hashgraph_tpu.protocol import (
    calculate_threshold_based_value,
    decide as scalar_decide,
)

THRESHOLDS = [2.0 / 3.0, 0.5, 0.6, 0.9, 1.0, 0.0, 0.61, 0.667]


def build_cases(threshold, n_max=12, tot_extra=2):
    """All (yes, tot, n, liveness, timeout) combos; tot may exceed n (more
    distinct voters than expected is representable in the reference)."""
    rows = []
    for n in range(1, n_max + 1):
        for tot in range(0, n + tot_extra + 1):
            for yes in range(0, tot + 1):
                for liveness in (False, True):
                    for is_timeout in (False, True):
                        rows.append((yes, tot, n, liveness, is_timeout))
    return np.array(rows, dtype=np.int64)


@pytest.mark.parametrize("threshold", THRESHOLDS)
def test_decide_kernel_matches_scalar_oracle(threshold):
    cases = build_cases(threshold)
    yes, tot, n = cases[:, 0], cases[:, 1], cases[:, 2]
    liveness, is_timeout = cases[:, 3].astype(bool), cases[:, 4].astype(bool)
    req = required_votes_np(n, threshold)

    decided, result = jax.jit(decide_kernel)(
        jnp.asarray(yes, jnp.int32),
        jnp.asarray(tot, jnp.int32),
        jnp.asarray(n, jnp.int32),
        jnp.asarray(req, jnp.int32),
        jnp.asarray(liveness),
        jnp.asarray(is_timeout),
    )
    decided = np.asarray(decided)
    result = np.asarray(result)

    for i in range(len(cases)):
        expected = scalar_decide(
            int(yes[i]), int(tot[i]), int(n[i]), threshold, bool(liveness[i]), bool(is_timeout[i])
        )
        got = bool(result[i]) if decided[i] else None
        assert got == expected, (
            f"mismatch at yes={yes[i]} tot={tot[i]} n={n[i]} t={threshold} "
            f"live={liveness[i]} timeout={is_timeout[i]}: kernel={got} oracle={expected}"
        )


def test_required_votes_matches_scalar_for_large_n():
    rng = np.random.default_rng(42)
    n = rng.integers(1, 2**30, size=2000)
    for threshold in THRESHOLDS:
        req = required_votes_np(n, threshold)
        for i in range(0, 2000, 97):
            assert req[i] == calculate_threshold_based_value(int(n[i]), threshold)


def test_large_n_randomized_parity():
    rng = np.random.default_rng(7)
    size = 5000
    n = rng.integers(3, 2**20, size=size)
    tot = (n * rng.random(size)).astype(np.int64)
    yes = (tot * rng.random(size)).astype(np.int64)
    liveness = rng.random(size) < 0.5
    is_timeout = rng.random(size) < 0.5
    threshold = 2.0 / 3.0
    req = required_votes_np(n, threshold)

    decided, result = jax.jit(decide_kernel)(
        jnp.asarray(yes, jnp.int32),
        jnp.asarray(tot, jnp.int32),
        jnp.asarray(n, jnp.int32),
        jnp.asarray(req, jnp.int32),
        jnp.asarray(liveness),
        jnp.asarray(is_timeout),
    )
    decided, result = np.asarray(decided), np.asarray(result)
    for i in range(0, size, 131):
        expected = scalar_decide(
            int(yes[i]), int(tot[i]), int(n[i]), threshold, bool(liveness[i]), bool(is_timeout[i])
        )
        got = bool(result[i]) if decided[i] else None
        assert got == expected


class TestStateUpdates:
    def test_decide_update_transitions_only_active(self):
        # slots: active-reaching, active-undecided, already failed, reached-no
        state = jnp.asarray([STATE_ACTIVE, STATE_ACTIVE, STATE_FAILED, STATE_REACHED_NO], jnp.int32)
        yes = jnp.asarray([3, 1, 3, 0], jnp.int32)
        tot = jnp.asarray([3, 1, 3, 3], jnp.int32)
        n = jnp.asarray([4, 4, 4, 4], jnp.int32)
        req = jnp.asarray(required_votes_np(np.array([4, 4, 4, 4]), 2 / 3), jnp.int32)
        liveness = jnp.asarray([True, True, True, True])

        new_state = decide_update(state, yes, tot, n, req, liveness)
        assert list(np.asarray(new_state)) == [
            STATE_REACHED_YES,  # 3 yes + 1 silent-as-yes -> 4 >= 3
            STATE_ACTIVE,  # 1 vote < quorum 3
            STATE_FAILED,  # untouched
            STATE_REACHED_NO,  # untouched
        ]

    def test_timeout_update_masks_and_fails(self):
        state = jnp.asarray([STATE_ACTIVE, STATE_ACTIVE, STATE_ACTIVE, STATE_REACHED_YES], jnp.int32)
        yes = jnp.asarray([1, 1, 2, 0], jnp.int32)
        tot = jnp.asarray([2, 3, 2, 0], jnp.int32)
        n = jnp.asarray([4, 4, 4, 4], jnp.int32)
        req = jnp.asarray(required_votes_np(np.array([4, 4, 4, 4]), 2 / 3), jnp.int32)
        liveness = jnp.asarray([True, True, False, True])
        # slot1: 1 yes 2 no 1 silent-as-yes -> 2-2 weighted tie, tot<n -> Failed
        # slot0: 1 yes 1 no 2 silent-as-yes -> 3 yes >= 3, 3 > 1 -> ReachedYes
        # slot2: masked out -> unchanged
        # slot3: already reached -> idempotent
        mask = jnp.asarray([True, True, False, True])

        new_state = timeout_update(state, yes, tot, n, req, liveness, mask)
        assert list(np.asarray(new_state)) == [
            STATE_REACHED_YES,
            STATE_FAILED,
            STATE_ACTIVE,
            STATE_REACHED_YES,
        ]
