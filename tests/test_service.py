"""Service-level behavior (reference: tests/consensus_service_tests.rs):
happy paths, event emission, every timeout branch, rejections, idempotency,
config resolution, query helpers, eviction, and scope lifecycle."""

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
    NetworkType,
    build_vote,
)
from hashgraph_tpu.errors import (
    ConsensusFailed,
    ConsensusNotReached,
    DuplicateVote,
    InsufficientVotesAtTimeout,
    ProposalAlreadyExist,
    ProposalExpired,
    SessionNotFound,
    UserAlreadyVoted,
)

from common import (
    NOW,
    cast_remote_vote,
    make_service,
    random_stub_signer,
    sibling_service,
)

SCOPE = "service_scope"
EXPIRATION = 120


def create(service, scope=SCOPE, n=3, config=None, liveness=True, now=NOW, expiration=EXPIRATION):
    request = CreateProposalRequest(
        name="Service Test",
        payload=b"payload",
        proposal_owner=service.signer().identity(),
        expected_voters_count=n,
        expiration_timestamp=expiration,
        liveness_criteria_yes=liveness,
    )
    return service.create_proposal_with_config(
        scope, request, config or ConsensusConfig.gossipsub(), now
    )


def drain_events(receiver):
    events = []
    while (item := receiver.try_recv()) is not None:
        events.append(item)
    return events


class TestBasicFlow:
    def test_create_cast_and_reach_consensus(self):
        service = make_service()
        proposal = create(service)
        vote = service.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
        assert vote.vote_owner == service.signer().identity()
        with pytest.raises(ConsensusNotReached):
            service.storage().get_consensus_result(SCOPE, proposal.proposal_id)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        assert service.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True

    def test_cast_vote_and_get_proposal_embeds_vote(self):
        service = make_service()
        proposal = create(service, n=5)
        updated = service.cast_vote_and_get_proposal(SCOPE, proposal.proposal_id, True, NOW)
        assert len(updated.votes) == 1
        assert updated.votes[0].vote_owner == service.signer().identity()

    def test_multi_scope_isolation(self):
        service = make_service()
        p1 = create(service, scope="scope_a")
        p2 = create(service, scope="scope_b")
        assert service.storage().get_session("scope_a", p2.proposal_id) is None
        assert service.storage().get_session("scope_b", p1.proposal_id) is None
        service.storage().delete_scope("scope_a")
        assert service.storage().get_session("scope_a", p1.proposal_id) is None
        assert service.storage().get_session("scope_b", p2.proposal_id) is not None

    def test_process_incoming_proposal_roundtrip(self):
        origin = make_service()
        proposal = create(origin, n=5)
        origin.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
        snapshot = origin.storage().get_proposal(SCOPE, proposal.proposal_id)

        receiver_service = make_service()
        receiver_service.process_incoming_proposal(SCOPE, snapshot.clone(), NOW)
        stored = receiver_service.storage().get_proposal(SCOPE, proposal.proposal_id)
        assert len(stored.votes) == 1
        assert stored.round == 2


class TestEvents:
    def test_consensus_reached_event_emitted(self):
        service = make_service()
        receiver = service.event_bus().subscribe()
        proposal = create(service)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        events = drain_events(receiver)
        assert (SCOPE, ConsensusReached(proposal.proposal_id, True, NOW)) in events

    def test_no_event_until_consensus(self):
        service = make_service()
        receiver = service.event_bus().subscribe()
        proposal = create(service, n=5)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        assert drain_events(receiver) == []

    def test_failed_event_on_timeout(self):
        service = make_service()
        receiver = service.event_bus().subscribe()
        proposal = create(service, n=4, liveness=True)
        # 1 YES, 2 NO, 1 silent-as-YES -> weighted tie -> Failed.
        for choice in (True, False, False):
            cast_remote_vote(service, SCOPE, proposal.proposal_id, choice, random_stub_signer())
        with pytest.raises(InsufficientVotesAtTimeout):
            service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60)
        events = drain_events(receiver)
        assert (SCOPE, ConsensusFailedEvent(proposal.proposal_id, NOW + 60)) in events


class TestTimeoutBranches:
    """reference: tests/consensus_service_tests.rs:303-843"""

    def test_timeout_already_reached_is_idempotent(self):
        service = make_service()
        proposal = create(service)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is True
        # Second call returns the same result (reference: :1219-1281).
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 61) is True

    def test_reach_yes_at_timeout_quorum_gate(self):
        # n=4, 2 YES before timeout: no quorum (2 < 3); at timeout the gate
        # opens and silent-as-YES pushes YES through.
        service = make_service()
        proposal = create(service, n=4, liveness=True)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        with pytest.raises(ConsensusNotReached):
            service.storage().get_consensus_result(SCOPE, proposal.proposal_id)
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is True

    def test_no_result_at_timeout(self):
        # n=4, liveness=False: 2 YES + 2 silent-as-NO -> weighted tie, total<n -> None.
        service = make_service()
        proposal = create(service, n=4, liveness=False)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        with pytest.raises(InsufficientVotesAtTimeout):
            service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60)
        with pytest.raises(ConsensusFailed):
            service.storage().get_consensus_result(SCOPE, proposal.proposal_id)

    def test_liveness_no_majority(self):
        # n=4, liveness=False: 1 YES, 1 NO, 2 silent-as-NO -> 3 NO >= 3 -> NO.
        service = make_service()
        proposal = create(service, n=4, liveness=False)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, proposal.proposal_id, False, random_stub_signer())
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is False

    def test_zero_votes_timeout_liveness_yes(self):
        # All silent, liveness=True: yes_weight = n >= required -> YES.
        service = make_service()
        proposal = create(service, n=4, liveness=True)
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is True

    def test_zero_votes_timeout_liveness_no(self):
        service = make_service()
        proposal = create(service, n=4, liveness=False)
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is False

    def test_p2p_timeout_variant(self):
        service = make_service()
        proposal = create(service, n=4, config=ConsensusConfig.p2p(), liveness=True)
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, random_stub_signer())
        assert service.handle_consensus_timeout(SCOPE, proposal.proposal_id, NOW + 60) is True

    def test_timeout_unknown_proposal(self):
        service = make_service()
        with pytest.raises(SessionNotFound):
            service.handle_consensus_timeout(SCOPE, 999, NOW)


class TestRejections:
    def test_user_already_voted_via_cast(self):
        service = make_service()
        proposal = create(service, n=5)
        service.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
        with pytest.raises(UserAlreadyVoted):
            service.cast_vote(SCOPE, proposal.proposal_id, False, NOW)

    def test_duplicate_incoming_vote(self):
        service = make_service()
        proposal = create(service, n=5)
        voter = random_stub_signer()
        cast_remote_vote(service, SCOPE, proposal.proposal_id, True, voter)
        snapshot = service.storage().get_proposal(SCOPE, proposal.proposal_id)
        dup = build_vote(snapshot, False, voter, NOW)
        with pytest.raises(DuplicateVote):
            service.process_incoming_vote(SCOPE, dup, NOW)

    def test_unknown_proposal_vote(self):
        service = make_service()
        create(service, n=5)
        orphan = build_vote(
            CreateProposalRequest(
                name="x",
                payload=b"",
                proposal_owner=b"o",
                expected_voters_count=3,
                expiration_timestamp=60,
                liveness_criteria_yes=True,
            ).into_proposal(NOW),
            True,
            random_stub_signer(),
            NOW,
        )
        with pytest.raises(SessionNotFound):
            service.process_incoming_vote(SCOPE, orphan, NOW)

    def test_duplicate_proposal(self):
        service = make_service()
        proposal = create(service, n=5)
        snapshot = service.storage().get_proposal(SCOPE, proposal.proposal_id)
        with pytest.raises(ProposalAlreadyExist):
            service.process_incoming_proposal(SCOPE, snapshot, NOW)

    def test_cast_on_expired_proposal(self):
        service = make_service()
        proposal = create(service, expiration=10)
        with pytest.raises(ProposalExpired):
            service.cast_vote(SCOPE, proposal.proposal_id, True, NOW + 11)

    def test_expired_incoming_proposal(self):
        origin = make_service()
        proposal = create(origin, expiration=10)
        snapshot = origin.storage().get_proposal(SCOPE, proposal.proposal_id)
        receiver = make_service()
        with pytest.raises(ProposalExpired):
            receiver.process_incoming_proposal(SCOPE, snapshot, NOW + 11)


class TestConfigResolution:
    """reference: tests/consensus_service_tests.rs:1332-1377 + src/service.rs:444-484"""

    def test_scope_config_used_when_no_override(self):
        service = make_service()
        service.scope(SCOPE).with_network_type(NetworkType.P2P).with_threshold(0.75).initialize()
        request = CreateProposalRequest(
            name="x",
            payload=b"",
            proposal_owner=service.signer().identity(),
            expected_voters_count=4,
            expiration_timestamp=EXPIRATION,
            liveness_criteria_yes=True,
        )
        proposal = service.create_proposal(SCOPE, request, NOW)
        config = service.storage().get_proposal_config(SCOPE, proposal.proposal_id)
        assert config.consensus_threshold == 0.75
        assert not config.use_gossipsub_rounds

    def test_gossipsub_default_without_scope_config(self):
        service = make_service()
        request = CreateProposalRequest(
            name="x",
            payload=b"",
            proposal_owner=service.signer().identity(),
            expected_voters_count=4,
            expiration_timestamp=EXPIRATION,
            liveness_criteria_yes=True,
        )
        proposal = service.create_proposal(SCOPE, request, NOW)
        config = service.storage().get_proposal_config(SCOPE, proposal.proposal_id)
        assert config.use_gossipsub_rounds
        assert config.consensus_threshold == 2.0 / 3.0
        # Timeout derived from the proposal's expiration window.
        assert config.consensus_timeout == float(EXPIRATION)

    def test_explicit_override_keeps_its_timeout(self):
        service = make_service()
        override = ConsensusConfig.gossipsub().with_timeout(7.0)
        proposal = create(service, config=override)
        config = service.storage().get_proposal_config(SCOPE, proposal.proposal_id)
        assert config.consensus_timeout == 7.0

    def test_liveness_always_from_proposal(self):
        service = make_service()
        override = ConsensusConfig.gossipsub().with_liveness_criteria(True)
        proposal = create(service, config=override, liveness=False)
        config = service.storage().get_proposal_config(SCOPE, proposal.proposal_id)
        assert config.liveness_criteria is False


class TestQueryHelpers:
    """reference: tests/consensus_service_tests.rs:1380-1629"""

    def test_get_proposal_and_errors(self):
        service = make_service()
        proposal = create(service)
        assert (
            service.storage().get_proposal(SCOPE, proposal.proposal_id).proposal_id
            == proposal.proposal_id
        )
        with pytest.raises(SessionNotFound):
            service.storage().get_proposal(SCOPE, 12345678)
        with pytest.raises(SessionNotFound):
            service.storage().get_consensus_result(SCOPE, 12345678)
        with pytest.raises(SessionNotFound):
            service.storage().get_proposal_config(SCOPE, 12345678)

    def test_get_active_and_reached_proposals(self):
        service = make_service()
        p_active = create(service, n=5)
        p_reached = create(service, n=1)
        cast_remote_vote(service, SCOPE, p_reached.proposal_id, True, random_stub_signer())

        active_ids = {p.proposal_id for p in service.storage().get_active_proposals(SCOPE)}
        assert p_active.proposal_id in active_ids
        assert p_reached.proposal_id not in active_ids

        reached = service.storage().get_reached_proposals(SCOPE)
        assert reached == {p_reached.proposal_id: True}

    def test_helpers_on_unknown_scope(self):
        service = make_service()
        assert service.storage().get_active_proposals("nope") == []
        assert service.storage().get_reached_proposals("nope") == {}

    def test_stats(self):
        service = make_service()
        p1 = create(service, n=5)
        p2 = create(service, n=1)
        cast_remote_vote(service, SCOPE, p2.proposal_id, True, random_stub_signer())
        p3 = create(service, n=4, liveness=False)
        cast_remote_vote(service, SCOPE, p3.proposal_id, True, random_stub_signer())
        cast_remote_vote(service, SCOPE, p3.proposal_id, True, random_stub_signer())
        with pytest.raises(InsufficientVotesAtTimeout):
            service.handle_consensus_timeout(SCOPE, p3.proposal_id, NOW + 60)

        stats = service.get_scope_stats(SCOPE)
        assert stats.total_sessions == 3
        assert stats.active_sessions == 1
        assert stats.consensus_reached == 1
        assert stats.failed_sessions == 1

        empty = service.get_scope_stats("unknown_scope")
        assert empty.total_sessions == 0

    def test_delete_scope_lifecycle(self):
        """reference: tests/consensus_service_tests.rs:1632-1735"""
        service = make_service()
        service.scope(SCOPE).with_threshold(0.9).initialize()
        proposal = create(service)
        service.storage().delete_scope(SCOPE)
        assert service.storage().get_session(SCOPE, proposal.proposal_id) is None
        assert service.storage().get_scope_config(SCOPE) is None
        # Scope behaves as never-initialized: new proposals start fresh.
        p2 = create(service)
        config = service.storage().get_proposal_config(SCOPE, p2.proposal_id)
        assert config.consensus_threshold == 2.0 / 3.0


class TestEviction:
    def test_trim_scope_sessions_keeps_newest(self):
        """reference: src/service.rs:512-522 — silent LRU-by-created_at."""
        service = make_service(max_sessions=3)
        kept = []
        for i in range(5):
            proposal = create(service, now=NOW + i)
            kept.append((proposal.proposal_id, NOW + i))
        sessions = service.storage().list_scope_sessions(SCOPE)
        assert len(sessions) == 3
        surviving = {s.proposal.proposal_id for s in sessions}
        expected = {pid for pid, ts in sorted(kept, key=lambda x: -x[1])[:3]}
        assert surviving == expected
