"""Scheme conformance: every ConsensusSignatureScheme implementation must
honor the same contract — sign→verify round-trips, scalar-vs-batch verdict
equivalence (including the async submit/collect pair), the ragged-input
zip-truncation rule, and malformed-length scheme errors — so the engine's
batched/pipelined ingest paths can treat schemes interchangeably
(reference: src/signing.rs:46-74)."""

import pytest

from hashgraph_tpu.errors import ConsensusSchemeError
from hashgraph_tpu.signing import (
    Ed25519ConsensusSigner,
    EthereumConsensusSigner,
    PendingVerdicts,
    StubConsensusSigner,
)
from hashgraph_tpu.signing import _ed25519 as ed_py
from hashgraph_tpu import native


def _make_stub():
    return StubConsensusSigner(b"\x07" * 20)


SCHEMES = [
    pytest.param(_make_stub, id="stub"),
    pytest.param(EthereumConsensusSigner.random, id="ethereum"),
    pytest.param(Ed25519ConsensusSigner.random, id="ed25519"),
]


def _batch(make_signer, n=6):
    """n signed items + a forged one + a cross-signed one."""
    signers = [make_signer() for _ in range(3)]
    idents, payloads, sigs = [], [], []
    for i in range(n):
        s = signers[i % 3]
        payload = b"payload-%d" % i
        idents.append(s.identity())
        payloads.append(payload)
        sigs.append(s.sign(payload))
    return idents, payloads, sigs


class TestSchemeConformance:
    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_sign_verify_roundtrip(self, make_signer):
        signer = make_signer()
        cls = type(signer)
        sig = signer.sign(b"hello")
        assert cls.verify(signer.identity(), b"hello", sig) is True
        assert cls.verify(signer.identity(), b"hellO", sig) is False

    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_wrong_identity_fails(self, make_signer):
        a, b = make_signer(), make_signer()
        if a.identity() == b.identity():  # stub factory is deterministic
            b = StubConsensusSigner(b"\x08" * 20)
        sig = a.sign(b"payload")
        assert type(a).verify(b.identity(), b"payload", sig) is False

    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_scalar_vs_batch_equivalence(self, make_signer):
        """verify_batch yields exactly what per-item verify would —
        verdict for verdict, scheme error for scheme error."""
        idents, payloads, sigs = _batch(make_signer)
        cls = type(make_signer())
        # Corrupt one signature, cross-wire another, malform a third.
        sigs[1] = bytes([sigs[1][0] ^ 1]) + sigs[1][1:]
        idents[2], idents[3] = idents[3], idents[2]
        sigs[4] = b"short"
        batch = cls.verify_batch(idents, payloads, sigs)
        assert len(batch) == len(idents)
        for ident, payload, sig, got in zip(idents, payloads, sigs, batch):
            try:
                want = cls.verify(ident, payload, sig)
            except ConsensusSchemeError as exc:
                want = exc
            if isinstance(want, ConsensusSchemeError):
                assert isinstance(got, ConsensusSchemeError)
            else:
                assert got is want, (got, want)

    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_submit_collect_matches_batch(self, make_signer):
        idents, payloads, sigs = _batch(make_signer)
        cls = type(make_signer())
        sigs[0] = bytes([sigs[0][0] ^ 1]) + sigs[0][1:]
        pend = cls.verify_batch_submit(idents, payloads, sigs)
        assert isinstance(pend, PendingVerdicts)
        got = pend.collect()
        want = cls.verify_batch(idents, payloads, sigs)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            if isinstance(w, ConsensusSchemeError):
                assert isinstance(g, ConsensusSchemeError)
            else:
                assert g is w
        # collect() is idempotent.
        assert pend.collect() is got

    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_ragged_inputs_zip_truncate(self, make_signer):
        """The base-class contract: ragged inputs truncate to the
        shortest list, never raise, never index past it."""
        idents, payloads, sigs = _batch(make_signer, n=4)
        cls = type(make_signer())
        out = cls.verify_batch(idents, payloads[:2], sigs)
        assert len(out) == 2
        assert all(v is True for v in out)
        pend = cls.verify_batch_submit(idents[:3], payloads, sigs)
        assert len(pend.collect()) == 3

    @pytest.mark.parametrize("make_signer", SCHEMES)
    def test_empty_batch(self, make_signer):
        cls = type(make_signer())
        assert cls.verify_batch([], [], []) == []
        assert cls.verify_batch_submit([], [], []).collect() == []


class TestLengthErrors:
    """Wrong-length identities/signatures are scheme ERRORS (distinct
    from a False verdict) for the fixed-length schemes."""

    @pytest.mark.parametrize(
        "make_signer", [SCHEMES[1], SCHEMES[2]]
    )
    def test_malformed_lengths_are_scheme_errors(self, make_signer):
        signer = make_signer()
        cls = type(signer)
        sig = signer.sign(b"p")
        with pytest.raises(ConsensusSchemeError):
            cls.verify(signer.identity(), b"p", b"\x01\x02")
        with pytest.raises(ConsensusSchemeError):
            cls.verify(b"\x01" * 5, b"p", sig)
        out = cls.verify_batch(
            [signer.identity(), b"\x01" * 5, signer.identity()],
            [b"p", b"p", b"p"],
            [sig, sig, b"xx"],
        )
        assert out[0] is True
        assert isinstance(out[1], ConsensusSchemeError)
        assert isinstance(out[2], ConsensusSchemeError)


class TestEd25519Specifics:
    def test_rfc8032_vectors(self):
        seed = bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
        )
        signer = Ed25519ConsensusSigner(seed)
        assert signer.identity().hex() == (
            "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
        )
        sig = signer.sign(b"")
        assert sig.hex() == (
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249"
            "01555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe2465514143"
            "8e7a100b"
        )
        assert Ed25519ConsensusSigner.verify(signer.identity(), b"", sig)

    def test_native_and_fallback_agree(self):
        """The pure-Python RFC 8032 fallback and the native core must be
        byte-identical on keys and signatures and agree on verdicts."""
        signer = Ed25519ConsensusSigner.random()
        seed = signer.private_key_bytes()
        msg = b"cross-check"
        sig = signer.sign(msg)
        assert ed_py.public_key(seed) == signer.identity()
        assert ed_py.sign(seed, msg) == sig
        assert ed_py.verify(signer.identity(), msg, sig)
        assert not ed_py.verify(signer.identity(), msg + b"!", sig)

    def test_non_canonical_scalar_rejected(self):
        """s >= L is the malleable form; RFC 8032 verifiers reject it."""
        signer = Ed25519ConsensusSigner.random()
        sig = signer.sign(b"m")
        s = int.from_bytes(sig[32:], "little")
        bumped = sig[:32] + (s + ed_py.L).to_bytes(32, "little")
        assert Ed25519ConsensusSigner.verify(signer.identity(), b"m", bumped) is False
        assert ed_py.verify(signer.identity(), b"m", bumped) is False

    def test_undecodable_points_are_false_not_errors(self):
        signer = Ed25519ConsensusSigner.random()
        sig = signer.sign(b"m")
        # A pubkey encoding with y >= p is non-canonical -> False.
        assert (
            Ed25519ConsensusSigner.verify(b"\xff" * 32, b"m", sig) is False
        )
        out = Ed25519ConsensusSigner.verify_batch(
            [b"\xff" * 32], [b"m"], [sig]
        )
        assert out == [False]

    @pytest.mark.skipif(not native.available(), reason="native runtime absent")
    def test_native_batch_mixed_verdicts_exact(self):
        """The randomized-linear-combination fast path must fall back to
        exact per-item verdicts when the combination fails."""
        signers = [Ed25519ConsensusSigner.random() for _ in range(4)]
        payloads = [b"m%d" % i for i in range(64)]
        idents = [signers[i % 4].identity() for i in range(64)]
        sigs = [signers[i % 4].sign(p) for i, p in enumerate(payloads)]
        bad = {3, 17, 40, 63}
        for i in bad:
            sigs[i] = bytes([sigs[i][0] ^ 1]) + sigs[i][1:]
        out = Ed25519ConsensusSigner.verify_batch(idents, payloads, sigs)
        for i, verdict in enumerate(out):
            assert verdict is (i not in bad)
