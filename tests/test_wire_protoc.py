"""Cross-implementation wire compatibility: hand-rolled codec vs protoc.

Compiles the shipped .proto with the system protoc at test time and checks
that google.protobuf's serialization of the same messages is byte-identical
to hashgraph_tpu.wire (and round-trips both directions). This is the interop
proof that votes signed by this framework verify anywhere and vice versa.
"""

import importlib.util
import os
import subprocess
import sys

import pytest

from hashgraph_tpu.wire import Proposal, Vote

PROTO_DIR = os.path.join(
    os.path.dirname(__file__), "..", "hashgraph_tpu", "protos"
)
PROTO = os.path.join(PROTO_DIR, "messages", "v1", "consensus.proto")


@pytest.fixture(scope="module")
def pb2(tmp_path_factory):
    try:
        import google.protobuf  # noqa: F401
    except ImportError:
        pytest.skip("protobuf runtime not available")
    out = tmp_path_factory.mktemp("pb2")
    try:
        subprocess.run(
            [
                "protoc",
                f"--proto_path={os.path.abspath(PROTO_DIR)}",
                f"--python_out={out}",
                os.path.abspath(PROTO),
            ],
            check=True,
            capture_output=True,
        )
    except (FileNotFoundError, subprocess.CalledProcessError) as exc:
        pytest.skip(f"protoc unavailable/failed: {exc}")
    module_path = out / "messages" / "v1" / "consensus_pb2.py"
    spec = importlib.util.spec_from_file_location("consensus_pb2", module_path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["consensus_pb2"] = module
    spec.loader.exec_module(module)
    return module


def sample_vote(i=1):
    return Vote(
        vote_id=0xDEAD0000 + i,
        vote_owner=bytes([i]) * 20,
        proposal_id=777,
        timestamp=1_700_000_000 + i,
        vote=i % 2 == 0,
        parent_hash=b"" if i == 1 else bytes([i - 1]) * 32,
        received_hash=bytes([i + 7]) * 32,
        vote_hash=bytes([i + 9]) * 32,
        signature=bytes([i + 11]) * 65,
    )


def to_pb_vote(pb2, v: Vote):
    out = pb2.Vote()
    out.vote_id = v.vote_id
    out.vote_owner = v.vote_owner
    out.proposal_id = v.proposal_id
    out.timestamp = v.timestamp
    out.vote = v.vote
    out.parent_hash = v.parent_hash
    out.received_hash = v.received_hash
    out.vote_hash = v.vote_hash
    out.signature = v.signature
    return out


class TestProtocParity:
    def test_vote_bytes_identical(self, pb2):
        for i in (1, 2, 3):
            ours = sample_vote(i)
            theirs = to_pb_vote(pb2, ours)
            assert ours.encode() == theirs.SerializeToString()

    def test_vote_default_fields_omitted(self, pb2):
        ours = Vote()  # all defaults -> empty encoding in proto3
        assert ours.encode() == pb2.Vote().SerializeToString() == b""

    def test_proposal_bytes_identical(self, pb2):
        ours = Proposal(
            name="quarterly-vote",
            payload=b"\x01\x02\x03",
            proposal_id=777,
            proposal_owner=b"O" * 20,
            votes=[sample_vote(1), sample_vote(2)],
            expected_voters_count=5,
            round=2,
            timestamp=1_700_000_000,
            expiration_timestamp=1_700_000_600,
            liveness_criteria_yes=True,
        )
        theirs = pb2.Proposal()
        theirs.name = ours.name
        theirs.payload = ours.payload
        theirs.proposal_id = ours.proposal_id
        theirs.proposal_owner = ours.proposal_owner
        for v in ours.votes:
            theirs.votes.append(to_pb_vote(pb2, v))
        theirs.expected_voters_count = ours.expected_voters_count
        theirs.round = ours.round
        theirs.timestamp = ours.timestamp
        theirs.expiration_timestamp = ours.expiration_timestamp
        theirs.liveness_criteria_yes = ours.liveness_criteria_yes
        assert ours.encode() == theirs.SerializeToString()

    def test_cross_decode(self, pb2):
        """Their bytes decode with our codec and vice versa."""
        ours = sample_vote(2)
        pb_bytes = to_pb_vote(pb2, ours).SerializeToString()
        decoded = Vote.decode(pb_bytes)
        assert decoded == ours

        their_vote = pb2.Vote()
        their_vote.ParseFromString(ours.encode())
        assert their_vote.vote_owner == ours.vote_owner
        assert their_vote.timestamp == ours.timestamp
