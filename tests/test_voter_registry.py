"""Voter-identity registry bounds under churn.

The pool interns owner bytes to dense gids for the columnar/lane machinery.
Without eviction a long-lived deployment with rotating voter populations
leaks host memory (one entry per identity ever seen). The registry is
refcounted by live slot-lane references: releasing a voter's last slot drops
the mapping and recycles the id, so steady-state size tracks the *live*
population, not the historical one.
"""

import numpy as np
import pytest

from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner, build_vote
from hashgraph_tpu.engine import ProposalPool, TpuConsensusEngine

from common import NOW, random_stub_signer


class TestPoolRegistryRefcounts:
    def test_release_evicts_unreferenced_gids_and_recycles_ids(self):
        pool = ProposalPool(8, 4)
        (slot_a,) = pool.allocate_batch(
            [b"a"], n=[3], req=[2], cap=[0], gossip=[True], liveness=[True],
            expiry=[NOW + 100], created_at=[NOW],
        )
        (slot_b,) = pool.allocate_batch(
            [b"b"], n=[3], req=[2], cap=[0], gossip=[True], liveness=[True],
            expiry=[NOW + 100], created_at=[NOW],
        )
        shared, only_a = b"voter-shared", b"voter-a"
        assert pool.lane_for(slot_a, shared) == 0
        assert pool.lane_for(slot_a, only_a) == 1
        assert pool.lane_for(slot_b, shared) == 0
        assert pool.live_voter_count == 2
        pool.release([slot_a])
        # only_a lost its last reference; shared is still held by slot_b.
        assert pool.live_voter_count == 1
        assert pool.voter_gid(shared) == pool.voter_gid(shared)
        # The freed id is recycled by the next fresh intern.
        before = pool.voter_gid_count
        pool.voter_gid(b"voter-new")
        assert pool.voter_gid_count == before
        pool.release([slot_b])
        assert pool.live_voter_count == 1  # voter-new (interned, never voted)

    def test_batch_lane_assignment_is_refcounted(self):
        pool = ProposalPool(8, 4)
        slots = pool.allocate_batch(
            [b"a", b"b"], n=[3, 3], req=[2, 2], cap=[0, 0],
            gossip=[True, True], liveness=[True, True],
            expiry=[NOW + 100] * 2, created_at=[NOW] * 2,
        )
        gids = [pool.voter_gid(b"v%d" % i) for i in range(3)]
        # v0 votes on both slots, v1/v2 on one each.
        batch_slots = np.array([slots[0], slots[1], slots[0], slots[1]])
        batch_gids = np.array([gids[0], gids[0], gids[1], gids[2]])
        lanes = pool.lanes_for_batch(batch_slots, batch_gids)
        assert (lanes >= 0).all()
        pool.release([slots[0]])
        # v0 still referenced by slots[1]; v1 fully released.
        assert pool.live_voter_count == 2  # v0 + v2
        pool.release([slots[1]])
        assert pool.live_voter_count == 0
        assert len(pool._free_gids) == pool.voter_gid_count


class TestStaleGids:
    def test_gids_live_mask(self):
        pool = ProposalPool(4, 4)
        (slot,) = pool.allocate_batch(
            [b"k"], n=[2], req=[2], cap=[0], gossip=[True], liveness=[True],
            expiry=[NOW + 100], created_at=[NOW],
        )
        gid = pool.voter_gid(b"transient")
        assert pool.lane_for(slot, b"transient") is not None
        assert pool.gids_live(np.array([gid, -1, 10_000])).tolist() == [
            True, False, False,
        ]
        pool.release([slot])
        # Freed id: live mask flips off even though the id is range-valid.
        assert pool.gids_live(np.array([gid])).tolist() == [False]

    def test_gids_live_native_matches_numpy(self):
        """The native fused liveness pass (auto-routed for batches >= 512)
        must agree with the numpy path on live, freed, recycled-generation,
        out-of-range, negative, and sentinel gids."""
        from hashgraph_tpu import native

        if not native.available():
            pytest.skip("native runtime absent: nothing to compare")
        rng = np.random.default_rng(77)
        pool = ProposalPool(8, 8)
        pool.allocate_batch(
            [("s", i) for i in range(8)], n=np.full(8, 8),
            req=np.full(8, 8), cap=np.full(8, 2),
            gossip=np.ones(8, bool), liveness=np.ones(8, bool),
            expiry=np.full(8, NOW + 100), created_at=np.full(8, NOW),
        )
        owner = lambda i: (i + 1).to_bytes(2, "little") * 10
        gids = np.array([pool.voter_gid(owner(i)) for i in range(120)])
        pool.lanes_for_batch(np.arange(40, dtype=np.int64) % 8, gids[:40])
        pool.release(list(range(8)))  # evicts the 40 referenced voters
        recycled = np.array([pool.voter_gid(owner(i)) for i in range(5)])
        qs = np.concatenate(
            [
                gids, recycled,
                np.array([-1, -9, 2**40, (1 << 33) | 3], np.int64),
                rng.integers(-(2**35), 2**35, 600),
            ]
        )
        assert len(qs) >= 512  # native-routed
        whole = pool.gids_live(qs)
        chunked = np.concatenate(  # forced numpy (below threshold)
            [pool.gids_live(qs[i : i + 128]) for i in range(0, len(qs), 128)]
        )
        assert (whole == chunked).all()
        # And with the native layer explicitly absent, same answer.
        orig = native.gids_live
        try:
            native.gids_live = lambda *a, **k: None
            assert (pool.gids_live(qs) == whole).all()
        finally:
            native.gids_live = orig

    def test_columnar_rejects_stale_gid_after_eviction(self):
        """A gid held across a release boundary must get a typed rejection,
        not silently attribute the vote to the id's next claimant."""
        from hashgraph_tpu import StatusCode

        engine = TpuConsensusEngine(random_stub_signer(), capacity=8, voter_capacity=4)
        request = CreateProposalRequest(
            name="p", payload=b"", proposal_owner=b"o",
            expected_voters_count=3, expiration_timestamp=1000,
            liveness_criteria_yes=True,
        )
        first = engine.create_proposal("s", request, NOW)
        stale = engine.voter_gid(b"old-voter")
        statuses = engine.ingest_columnar(
            "s",
            np.array([first.proposal_id]),
            np.array([stale]),
            np.array([True]),
            NOW + 1,
        )
        assert statuses[0] in (int(StatusCode.OK), int(StatusCode.ALREADY_REACHED))
        engine.delete_scope("s")  # releases the slot; old-voter fully freed
        second = engine.create_proposal("s2", request, NOW)
        statuses = engine.ingest_columnar(
            "s2",
            np.array([second.proposal_id]),
            np.array([stale]),
            np.array([True]),
            NOW + 1,
        )
        assert statuses[0] == int(StatusCode.EMPTY_VOTE_OWNER)

    def test_columnar_rejects_stale_gid_after_recycling(self):
        """The generation tag makes a stale gid detectable even after its
        index has been recycled to a NEW owner — the r4 lifetime contract
        allowed silent misattribution to the new claimant there; now it is
        a typed rejection, and the new claimant's own gid is unaffected."""
        from hashgraph_tpu import StatusCode

        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=4
        )
        request = CreateProposalRequest(
            name="p", payload=b"", proposal_owner=b"o",
            expected_voters_count=3, expiration_timestamp=1000,
            liveness_criteria_yes=True,
        )
        first = engine.create_proposal("s", request, NOW)
        stale = engine.voter_gid(b"old-voter")
        statuses = engine.ingest_columnar(
            "s",
            np.array([first.proposal_id]),
            np.array([stale]),
            np.array([True]),
            NOW + 1,
        )
        assert statuses[0] == int(StatusCode.OK)
        engine.delete_scope("s")  # releases the slot; old-voter's index freed
        second = engine.create_proposal("s2", request, NOW)
        fresh = engine.voter_gid(b"new-claimant")  # recycles the index
        assert (fresh & 0xFFFFFFFF) == (stale & 0xFFFFFFFF)  # same index
        assert fresh != stale  # different generation
        statuses = engine.ingest_columnar(
            "s2",
            np.array([second.proposal_id, second.proposal_id]),
            np.array([stale, fresh]),
            np.array([True, True]),
            NOW + 1,
        )
        assert statuses[0] == int(StatusCode.EMPTY_VOTE_OWNER)
        assert statuses[1] == int(StatusCode.OK)

    def test_clear_voter_registry_keeps_stale_gids_rejected(self):
        """The clear raises the generation floor: a pre-clear gid must keep
        rejecting rather than become bit-identical to the first post-clear
        claimant's gid."""
        pool = ProposalPool(4, 4)
        stale = pool.voter_gid(b"old")
        pool.clear_voter_registry()
        fresh = pool.voter_gid(b"new")
        assert fresh != stale
        assert pool.gids_live(np.array([stale, fresh])).tolist() == [
            False, True,
        ]
        assert pool.owner_of_gid(fresh) == b"new"

    def test_lanes_for_batch_refuses_freed_and_stale_gids(self):
        """Pool-layer gate: a freed or stale-generation in-range gid must
        not claim a lane — storing it would decrement the recycled index's
        refcount on slot release and could evict a live voter."""
        pool = ProposalPool(8, 4)
        slot_a, slot_b = pool.allocate_batch(
            [b"a", b"b"], n=[3, 3], req=[2, 2], cap=[0, 0],
            gossip=[True, True], liveness=[True, True],
            expiry=[NOW + 100] * 2, created_at=[NOW] * 2,
        )
        stale = pool.voter_gid(b"v")
        assert pool.lanes_for_batch(
            np.array([slot_a]), np.array([stale])
        ).tolist() == [0]
        pool.release([slot_a])  # frees v's index
        assert pool.lanes_for_batch(
            np.array([slot_b]), np.array([stale])
        ).tolist() == [-1]
        fresh = pool.voter_gid(b"w")  # recycles the index, new generation
        lanes = pool.lanes_for_batch(
            np.array([slot_b, slot_b]), np.array([stale, fresh])
        )
        assert lanes.tolist() == [-1, 0]
        # Releasing slot_b evicts exactly the one counted reference.
        pool.release([slot_b])
        assert pool.live_voter_count == 0


class TestSteadyStateSoak:
    def test_churn_waves_hold_every_resource_steady(self):
        """Leak regression gate: config-5-style churn waves (multi-scope
        registration -> columnar ingest with wire retention -> scope
        deletion) must hold the voter registry, pid tables, record/index
        maps, retained-wire bytes, free-list, and host heap steady across
        waves — any unbounded growth fails the assertions, not just a
        documentation claim. (tracemalloc, not ru_maxrss: the latter is a
        process-lifetime high-water mark that earlier tests in the same
        run would mask.)"""
        import tracemalloc

        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=128, voter_capacity=8
        )
        scopes = [f"s{i}" for i in range(16)]
        request = CreateProposalRequest(
            name="p", payload=b"", proposal_owner=b"o",
            expected_voters_count=4, expiration_timestamp=1000,
            liveness_criteria_yes=True,
        )

        def wave(w: int) -> dict:
            # All interned owners vote: an interned-but-never-voted id has
            # no slot references to trigger eviction (documented; reclaim
            # via clear_voter_registry at a quiesce point).
            owners = [b"w%03d-v%d" % (w, i) for i in range(3)]
            gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
            batches = engine.create_proposals_multi(
                [(s, [request] * 4) for s in scopes], NOW
            )
            pids, sidx = [], []
            for k, proposals in enumerate(batches):
                pids.extend(p.proposal_id for p in proposals)
                sidx.extend([k] * len(proposals))
            pids = np.repeat(np.array(pids, np.int64), 3)
            sidx = np.repeat(np.array(sidx, np.int64), 3)
            col_gids = np.tile(gids[:3], 16 * 4)
            vals = np.ones(len(pids), bool)
            width = 40
            statuses = engine.ingest_columnar_multi(
                scopes, sidx, pids, col_gids, vals, NOW + 1,
                wire_votes=(
                    np.zeros(len(pids) * width, np.uint8),
                    np.arange(len(pids) + 1, dtype=np.int64) * width,
                ),
            )
            assert (statuses == int(StatusCode.OK)).all()
            engine.delete_scopes(scopes)
            pool = engine.pool()
            retained_bytes = sum(
                len(blob)
                for record in engine._records.values()
                for _, blob, _ in record.retained_wire
            )
            return {
                "gid_space": pool.voter_gid_count,
                "live_voters": pool.live_voter_count,
                "free_slots": pool.free_slots,
                "records": len(engine._records),
                "index": len(engine._index),
                "pid_tables": len(engine._pid_tables),
                "retained_bytes": retained_bytes,
                "heap": tracemalloc.get_traced_memory()[0],
            }

        from hashgraph_tpu import StatusCode

        tracemalloc.start()
        try:
            baseline = None
            for w in range(12):
                snap = wave(w)
                if w < 2:
                    baseline = snap  # allow first-wave warmup allocations
                    continue
                assert snap["gid_space"] <= 16, snap
                assert snap["live_voters"] <= 8, snap
                assert snap["free_slots"] == 128, snap
                assert snap["records"] == 0, snap
                assert snap["index"] == 0, snap
                assert snap["pid_tables"] == 0, snap
                assert snap["retained_bytes"] == 0, snap
                # Steady state: the live heap stops climbing after warmup
                # (1 MB slack for allocator/cache noise).
                assert snap["heap"] <= baseline["heap"] + 1_048_576, (
                    snap["heap"], baseline["heap"],
                )
        finally:
            tracemalloc.stop()


class TestEngineChurn:
    def test_rotating_voter_population_holds_registry_steady(self):
        """100 generations of 8 fresh voters each; scope deletion after each
        generation must keep the registry at one live generation, not 800
        identities."""
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=32, voter_capacity=16
        )
        sizes = []
        for gen in range(100):
            scope = f"gen-{gen}"
            request = CreateProposalRequest(
                name="p",
                payload=b"",
                proposal_owner=b"owner",
                expected_voters_count=8,
                expiration_timestamp=1000,
                liveness_criteria_yes=True,
            )
            proposal = engine.create_proposal(scope, request, NOW)
            voters = [StubConsensusSigner(b"g%03d-v%d" % (gen, i)) for i in range(8)]
            for voter in voters:
                current = engine.get_proposal(scope, proposal.proposal_id)
                vote = build_vote(current, True, voter, NOW + 1)
                engine.process_incoming_vote(scope, vote, NOW + 2)
            assert engine.get_consensus_result(scope, proposal.proposal_id) is True
            engine.delete_scope(scope)
            sizes.append(engine.pool().live_voter_count)
        # Live mappings never accumulate across generations...
        assert max(sizes) <= 16, sizes
        # ...and the id space stops growing once recycling kicks in.
        assert engine.pool().voter_gid_count <= 32
