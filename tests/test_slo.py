"""The SLO plane: windowed quantile sketches, the multi-window
burn-rate alert state machine, bounded incident capture, exemplar text
round-trips, and torn-free concurrent sidecar scrapes under ingest.

Every clock in these tests is injected (a mutable float), so alert
trajectories are exact — no sleeps, no wall-clock flakes."""

import json
import os
import threading
import urllib.request

import pytest

from hashgraph_tpu.obs import MetricsSidecar
from hashgraph_tpu.obs.prometheus import parse_exemplars, render
from hashgraph_tpu.obs.registry import (
    DEFAULT_TIME_BUCKETS,
    MetricsRegistry,
    quantile_from,
)
from hashgraph_tpu.obs.slo import (
    DEFAULT_BURN_THRESHOLD,
    IncidentCapture,
    SloEngine,
    WindowedHistogram,
)


class Clock:
    """An injectable monotonic clock the tests advance explicitly."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


# ── WindowedHistogram ──────────────────────────────────────────────────


class TestWindowedHistogram:
    def test_window_counts_and_quantile(self):
        wh = WindowedHistogram(slice_seconds=10.0, max_age=100.0)
        for k in range(10):
            wh.observe(0.004, 1000.0 + 10 * k)
        counts, total, breaching = wh.window_counts(100.0, 1100.0)
        assert total == 10 and breaching == 0
        q = wh.quantile(0.99, 100.0, 1100.0)
        assert 0.002 < q <= 0.008  # inside the 4ms log bucket's bounds

    def test_old_slices_age_out(self):
        wh = WindowedHistogram(slice_seconds=10.0, max_age=50.0)
        wh.observe(0.001, 1000.0)
        wh.observe(0.001, 1100.0)  # prunes the first slice (>max_age)
        _, total, _ = wh.window_counts(1000.0, 1100.0)
        assert total == 1

    def test_narrow_window_excludes_older_slices(self):
        wh = WindowedHistogram(slice_seconds=10.0, max_age=1000.0)
        wh.observe(0.001, 1000.0, breaching=False)
        wh.observe(0.5, 1200.0, breaching=True)
        _, total_fast, breach_fast = wh.window_counts(50.0, 1200.0)
        assert (total_fast, breach_fast) == (1, 1)
        _, total_all, breach_all = wh.window_counts(1000.0, 1200.0)
        assert (total_all, breach_all) == (2, 1)

    def test_summary_shape(self):
        wh = WindowedHistogram()
        wh.observe(0.01, 1000.0)
        s = wh.summary(300.0, 1000.0)
        assert s["count"] == 1
        assert set(s) >= {"count", "p50", "p95", "p99"}

    def test_quantile_from_interpolates(self):
        bounds = DEFAULT_TIME_BUCKETS
        counts = [0] * (len(bounds) + 1)
        idx = next(i for i, b in enumerate(bounds) if 0.01 <= b)
        counts[idx] = 100
        q50 = quantile_from(bounds, counts, 100, 0.50)
        lo = bounds[idx - 1] if idx else 0.0
        assert lo < q50 <= bounds[idx]

    def test_empty_quantile_is_zero(self):
        wh = WindowedHistogram()
        assert wh.quantile(0.99, 300.0, 1000.0) == 0.0


# ── Burn-rate alert state machine ──────────────────────────────────────


class TestBurnRateAlerts:
    def _engine(self, clock, **kw):
        return SloEngine(clock=clock, **kw)

    def test_alert_fires_only_when_both_windows_burn(self, tmp_path):
        clock = Clock()
        slo = self._engine(clock)
        # An hour of healthy traffic fills the slow window.
        for _ in range(30):
            slo.observe("s", 0.005, objective_s=0.05, now=clock())
            clock.tick(30.0)
        assert slo.state(now=clock())["alerts_firing"] == []
        # Sustained breaches push BOTH windows over the threshold.
        for _ in range(10):
            slo.observe("s", 0.5, objective_s=0.05, now=clock())
            clock.tick(10.0)
        state = slo.state(now=clock())
        assert state["alerts_firing"] == ["s"]
        scope = state["scopes"]["s"]
        assert scope["burn_fast"] >= DEFAULT_BURN_THRESHOLD
        assert scope["burn_slow"] >= DEFAULT_BURN_THRESHOLD
        assert scope["alerts_total"] == 1

    def test_alert_clears_when_fast_window_recovers(self):
        clock = Clock()
        slo = self._engine(clock)
        for _ in range(30):
            slo.observe("s", 0.005, objective_s=0.05, now=clock())
            clock.tick(30.0)
        for _ in range(10):
            slo.observe("s", 0.5, objective_s=0.05, now=clock())
            clock.tick(10.0)
        assert slo.state(now=clock())["alerts_firing"] == ["s"]
        clock.tick(400.0)  # breaches age out of the fast window
        slo.observe("s", 0.005, objective_s=0.05, now=clock())
        state = slo.state(now=clock())
        assert state["alerts_firing"] == []
        # One firing EPISODE, not one per breaching observation.
        assert state["scopes"]["s"]["alerts_total"] == 1

    def test_short_blip_does_not_fire(self):
        clock = Clock()
        slo = self._engine(clock)
        for _ in range(200):
            slo.observe("s", 0.005, objective_s=0.05, now=clock())
            clock.tick(15.0)
        # One breach in 200: the slow-window burn stays far under 14.4.
        slo.observe("s", 0.5, objective_s=0.05, now=clock())
        assert slo.state(now=clock())["alerts_firing"] == []

    def test_best_effort_scopes_never_alert(self):
        clock = Clock()
        slo = self._engine(clock)
        for _ in range(50):
            slo.observe("free", 10.0, now=clock())  # no objective
            clock.tick(5.0)
        state = slo.state(now=clock())
        assert state["alerts_firing"] == []
        assert state["scopes"]["free"]["objective_s"] is None

    def test_disabled_kill_switch_skips_everything(self):
        clock = Clock()
        slo = self._engine(clock)
        slo.enabled = False
        slo.observe("s", 9.9, objective_s=0.01, now=clock())
        state = slo.state(now=clock())
        assert state["scopes"] == {} and state["global"]["count"] == 0
        slo.enabled = True
        slo.observe("s", 9.9, objective_s=0.01, now=clock())
        assert slo.state(now=clock())["global"]["count"] == 1

    def test_scope_lru_pins_objective_scopes(self):
        clock = Clock()
        slo = self._engine(clock, max_scopes=4)
        slo.observe("pinned", 0.1, objective_s=0.05, now=clock())
        for k in range(32):
            slo.observe(f"churn-{k}", 0.001, now=clock())
        state = slo.state(now=clock())
        assert len(state["scopes"]) <= 4
        assert "pinned" in state["scopes"]

    def test_per_shard_windows_tracked(self):
        clock = Clock()
        slo = self._engine(clock)
        slo.observe("a", 0.001, shard="s0", now=clock())
        slo.observe("b", 0.2, shard="s1", now=clock())
        shards = slo.state(now=clock())["shards"]
        assert set(shards) == {"s0", "s1"}
        assert shards["s1"]["p99"] > shards["s0"]["p99"]

    def test_registry_families_installed(self):
        from hashgraph_tpu.obs.slo import (
            SLO_ALERTS_FIRING,
            SLO_BREACHES_TOTAL,
            SLO_DECISION_P99_SECONDS,
        )

        clock = Clock()
        reg = MetricsRegistry()
        slo = SloEngine(registry=reg, clock=clock)
        for _ in range(30):
            slo.observe("s", 0.005, shard="sh0", objective_s=0.05, now=clock())
            clock.tick(30.0)
        for _ in range(10):
            slo.observe("s", 0.5, shard="sh0", objective_s=0.05, now=clock())
            clock.tick(10.0)
        text = reg.render_prometheus()
        assert f"{SLO_BREACHES_TOTAL} 10" in text
        assert f"{SLO_ALERTS_FIRING} 1" in text
        assert f'{SLO_DECISION_P99_SECONDS}{{shard="sh0"}}' in text
        assert f'{SLO_DECISION_P99_SECONDS}{{scope="s"}}' in text


# ── Incident capture ───────────────────────────────────────────────────


class TestIncidentCapture:
    def test_capture_writes_linked_artifacts(self, tmp_path):
        clock = Clock()
        cap = IncidentCapture(str(tmp_path), clock=clock)
        path = cap.capture(
            "slo_breach",
            scope="s",
            shard="sh0",
            trace_hex="ab" * 16,
            latency_s=0.5,
            objective_s=0.05,
        )
        assert path is not None
        meta = json.load(open(os.path.join(path, "incident.json")))
        assert meta["trace_id"] == "ab" * 16
        assert meta["latency_s"] == 0.5 and meta["objective_s"] == 0.05
        doc = json.load(open(os.path.join(path, "trace.json")))
        assert "traceEvents" in doc  # Perfetto/chrome://tracing loadable
        assert os.path.exists(os.path.join(path, "flight.jsonl"))

    def test_cooldown_collapses_breach_storm(self, tmp_path):
        clock = Clock()
        cap = IncidentCapture(str(tmp_path), cooldown_s=60.0, clock=clock)
        assert cap.capture("slo_breach", scope="s") is not None
        assert cap.capture("slo_breach", scope="s") is None  # cooled down
        clock.tick(61.0)
        assert cap.capture("slo_breach", scope="s") is not None
        assert len(cap.incidents()) == 2

    def test_max_incidents_gc_keeps_newest(self, tmp_path):
        clock = Clock()
        cap = IncidentCapture(
            str(tmp_path), max_incidents=3, cooldown_s=0.0, clock=clock
        )
        for k in range(6):
            clock.tick(1.0)
            cap.capture("slo_breach", scope=f"s{k}")
        names = cap.incidents()
        assert len(names) == 3
        assert names[-1].startswith("incident-000006")

    def test_disabled_without_root(self, monkeypatch):
        monkeypatch.delenv("HASHGRAPH_INCIDENT_DIR", raising=False)
        cap = IncidentCapture(None)
        assert not cap.enabled
        assert cap.capture("slo_breach", scope="s") is None

    def test_engine_captures_exactly_once_per_cooldown(self, tmp_path):
        clock = Clock()
        cap = IncidentCapture(str(tmp_path), cooldown_s=10**9, clock=clock)
        slo = SloEngine(clock=clock, capture=cap)
        for _ in range(20):
            slo.observe("s", 0.5, objective_s=0.05, now=clock())
            clock.tick(10.0)
        assert len(cap.incidents()) == 1


# ── Engine wiring: ScopeConfig objective -> decided() -> slo_engine ────


class TestEngineWiring:
    def test_decide_p99_ms_config_field(self):
        from hashgraph_tpu.scope_config import ScopeConfig, ScopeConfigBuilder

        cfg = ScopeConfigBuilder().with_decide_p99_ms(50.0).build()
        assert cfg.decide_p99_ms == 50.0
        assert cfg.clone().decide_p99_ms == 50.0
        with pytest.raises(ValueError):
            ScopeConfig(decide_p99_ms=-1.0).validate()

    def test_decision_feeds_global_slo_engine(self):
        from hashgraph_tpu import (
            CreateProposalRequest,
            build_vote,
        )
        from hashgraph_tpu.engine import TpuConsensusEngine
        from hashgraph_tpu.obs import slo_engine
        from hashgraph_tpu.scope_config import ScopeConfigBuilder

        from common import NOW, random_stub_signer

        slo_engine.reset()
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        engine.set_scope_config(
            "slo-scope", ScopeConfigBuilder().with_decide_p99_ms(50.0).build()
        )
        request = CreateProposalRequest("p", b"", b"o", 2, 100, True)
        pid = engine.create_proposal("slo-scope", request, NOW).proposal_id
        for _ in range(2):
            vote = build_vote(
                engine.get_proposal("slo-scope", pid),
                True,
                random_stub_signer(),
                NOW + 1,
            )
            engine.ingest_votes([("slo-scope", vote)], NOW + 1)
        state = slo_engine.state()
        entry = state["scopes"].get("slo-scope")
        assert entry is not None and entry["count"] >= 1
        # The declared objective arrived in seconds, and the decision's
        # trace id landed as the latency histogram's exemplar.
        assert entry["objective_s"] == pytest.approx(0.05)
        from hashgraph_tpu.obs import DECISION_LATENCY

        exemplars = engine.metrics.histogram(DECISION_LATENCY).exemplars()
        assert any(
            entry_[1] is not None and len(entry_[1]) == 32
            for entry_ in exemplars.values()
        )
        slo_engine.reset()


# ── Exemplars: render + text round-trip ────────────────────────────────


class TestExemplars:
    def test_exemplar_round_trip(self):
        reg = MetricsRegistry()
        hist = reg.histogram("rt_seconds")
        hist.observe(0.004, exemplar="fe" * 16)
        hist.observe(0.004)  # no exemplar: the recorded one sticks
        text = render(reg)
        found = parse_exemplars(text)
        assert "rt_seconds_bucket" in found
        (ex,) = found["rt_seconds_bucket"]
        assert ex["trace_id"] == "fe" * 16
        assert ex["value"] == pytest.approx(0.004)
        assert ex["le"] is not None

    def test_exemplar_per_bucket_latest_wins(self):
        reg = MetricsRegistry()
        hist = reg.histogram("latest_seconds")
        hist.observe(0.004, exemplar="aa" * 16)
        hist.observe(0.004, exemplar="bb" * 16)
        exemplars = hist.exemplars()
        (entry,) = exemplars.values()
        assert entry[1] == "bb" * 16

    def test_no_exemplar_no_suffix(self):
        reg = MetricsRegistry()
        reg.histogram("plain_seconds").observe(0.004)
        assert parse_exemplars(render(reg)) == {}


# ── Concurrent sidecar scrapes during ingest ───────────────────────────


class TestConcurrentScrapes:
    def test_scrapes_never_tear_during_ingest(self):
        reg = MetricsRegistry()
        counter = reg.counter("ingest_total")
        hist = reg.histogram("ingest_seconds")
        clock = Clock()
        slo = SloEngine(registry=reg, clock=clock)
        sidecar = MetricsSidecar(reg, slo_fn=lambda: slo.state(now=clock()))
        host, port = sidecar.start()
        stop = threading.Event()
        errors: list = []

        def ingest():
            k = 0
            while not stop.is_set():
                counter.inc()
                hist.observe(0.001 * (k % 7 + 1), exemplar=f"{k:032x}")
                slo.observe(
                    f"s{k % 3}", 0.002, objective_s=0.05, now=clock()
                )
                k += 1

        def scrape():
            try:
                for _ in range(25):
                    with urllib.request.urlopen(
                        f"http://{host}:{port}/metrics", timeout=10
                    ) as rsp:
                        text = rsp.read().decode()
                    # Torn text would break these invariants: complete
                    # final line, TYPE before samples, and a histogram's
                    # +Inf bucket equal to its _count (single-moment
                    # snapshot per histogram).
                    assert text.endswith("\n")
                    assert text.index(
                        "# TYPE ingest_seconds histogram"
                    ) < text.index("ingest_seconds_bucket")
                    inf = count = None
                    for line in text.splitlines():
                        if line.startswith('ingest_seconds_bucket{le="+Inf"'):
                            inf = int(line.split(" # ")[0].rsplit(" ", 1)[-1])
                        elif line.startswith("ingest_seconds_count"):
                            count = int(line.rsplit(" ", 1)[-1])
                    assert inf is not None and inf == count
                    with urllib.request.urlopen(
                        f"http://{host}:{port}/slo", timeout=10
                    ) as rsp:
                        body = json.loads(rsp.read())
                    # /slo and /metrics stay mutually consistent: both
                    # surfaces exist and agree the plane is enabled.
                    assert body["enabled"] is True
                    assert set(body["scopes"]) <= {"s0", "s1", "s2"}
            except Exception as exc:  # pragma: no cover - failure detail
                errors.append(exc)

        writer = threading.Thread(target=ingest, daemon=True)
        scrapers = [
            threading.Thread(target=scrape, daemon=True) for _ in range(4)
        ]
        writer.start()
        for t in scrapers:
            t.start()
        for t in scrapers:
            t.join(timeout=60)
        stop.set()
        writer.join(timeout=10)
        sidecar.stop()
        assert not errors, errors[0]

    def test_slo_endpoint_serves_engine_state(self):
        clock = Clock()
        reg = MetricsRegistry()
        slo = SloEngine(registry=reg, clock=clock)
        slo.observe("s", 0.005, objective_s=0.05, now=clock())
        sidecar = MetricsSidecar(reg, slo_fn=lambda: slo.state(now=clock()))
        host, port = sidecar.start()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/slo", timeout=5
            ) as rsp:
                body = json.loads(rsp.read())
        finally:
            sidecar.stop()
        assert body["scopes"]["s"]["objective_s"] == 0.05
        assert body["alerts_firing"] == []
