"""Observability subsystem (hashgraph_tpu.obs): metrics registry,
Prometheus exposition, proposal timelines, flight recorder, the HTTP
sidecar, and the bridge GET_METRICS opcode.

The registry unit tests use FRESH MetricsRegistry instances (the process
default accumulates across the whole test session by design); engine-level
tests assert deltas or per-proposal readouts, never absolute global
counter values.
"""

import json
import math
import threading
import urllib.request

import pytest

from hashgraph_tpu import CreateProposalRequest, build_vote
from hashgraph_tpu.bridge import BridgeClient, BridgeError, BridgeServer
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.obs import (
    DECISION_LATENCY,
    DECISIONS_TOTAL,
    TIMEOUTS_FIRED_TOTAL,
    FlightRecorder,
    MetricsRegistry,
    MetricsSidecar,
    log_buckets,
)
from hashgraph_tpu.obs import flight_recorder as global_flight
from hashgraph_tpu.obs import registry as global_registry
from hashgraph_tpu.obs.prometheus import sanitize

from common import NOW, random_stub_signer


def fresh_engine(**kwargs) -> TpuConsensusEngine:
    kwargs.setdefault("capacity", 8)
    kwargs.setdefault("voter_capacity", 8)
    return TpuConsensusEngine(random_stub_signer(), **kwargs)


def make_request(expected: int = 2, expiry: int = 100) -> CreateProposalRequest:
    return CreateProposalRequest("p", b"", b"o", expected, expiry, True)


class TestRegistry:
    def test_counter(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(4)
        assert reg.counter("c").value == 5

    def test_gauge_set_and_providers_sum(self):
        reg = MetricsRegistry()
        reg.gauge("g").set(2)
        reg.register_gauge("g", lambda: 3)
        reg.register_gauge("g", lambda: 5)
        assert reg.gauge("g").value == 10

    def test_gauge_provider_dies_with_owner(self):
        reg = MetricsRegistry()

        class Owner:
            pass

        owner = Owner()
        reg.register_gauge("g", lambda: 7, owner=owner)
        assert reg.gauge("g").value == 7
        del owner
        assert reg.gauge("g").value == 0

    def test_gauge_unregister_handle(self):
        reg = MetricsRegistry()
        handle = reg.register_gauge("g", lambda: 7)
        handle.unregister()
        assert reg.gauge("g").value == 0

    def test_gauge_provider_exception_does_not_poison(self):
        reg = MetricsRegistry()
        reg.register_gauge("g", lambda: 1 / 0)
        reg.register_gauge("g", lambda: 3)
        assert reg.gauge("g").value == 3

    def test_log_buckets(self):
        bounds = log_buckets(1e-3, 1.0, factor=10)
        assert bounds == (1e-3, 1e-2, 1e-1, 1.0)
        with pytest.raises(ValueError):
            log_buckets(0, 1)

    def test_histogram_buckets_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", bounds=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0, 100.0):
            h.observe(v)
        buckets = h.buckets()
        assert buckets == [(1.0, 1), (2.0, 2), (4.0, 3), (math.inf, 4)]
        assert h.count == 4
        assert h.sum == pytest.approx(105.0)

    def test_histogram_quantiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", bounds=log_buckets(1e-3, 10.0))
        for _ in range(100):
            h.observe(0.01)
        # All mass in the bucket containing 0.01: the quantile estimate
        # must land inside that bucket's bounds.
        p50 = h.quantile(0.5)
        assert 0.004 <= p50 <= 0.016
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["p50"] == pytest.approx(p50)

    def test_histogram_empty_quantile(self):
        assert MetricsRegistry().histogram("h").quantile(0.99) == 0.0

    def test_histogram_bounds_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.histogram("h", bounds=(1.0, 2.0))
        assert reg.histogram("h").bounds == (1.0, 2.0)  # no bounds: reuse
        assert reg.histogram("h", bounds=(1.0, 2.0)) is reg.histogram("h")
        with pytest.raises(ValueError):
            reg.histogram("h", bounds=(1.0, 4.0))

    def test_concurrent_writers(self):
        reg = MetricsRegistry()
        counter = reg.counter("c")
        hist = reg.histogram("h", bounds=(1.0, 10.0))
        threads = [
            threading.Thread(
                target=lambda: [
                    (counter.inc(), hist.observe(0.5)) for _ in range(5_000)
                ]
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counter.value == 40_000
        assert hist.count == 40_000
        assert hist.buckets()[0] == (1.0, 40_000)

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.gauge("g").set(2)
        reg.histogram("h").observe(0.1)
        snap = reg.snapshot()
        assert snap["counters"] == {"c": 1}
        assert snap["gauges"] == {"g": 2.0}
        assert set(snap["histograms"]["h"]) == {"count", "sum", "p50", "p90", "p99"}
        json.dumps(snap)  # must be JSON-serializable as-is


class TestPrometheusRender:
    def test_render_families(self):
        reg = MetricsRegistry()
        reg.counter("requests_total").inc(3)
        reg.gauge("live").set(2)
        h = reg.histogram("latency_seconds", bounds=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.render_prometheus()
        assert "# TYPE requests_total counter\nrequests_total 3" in text
        assert "# TYPE live gauge\nlive 2" in text
        assert '# TYPE latency_seconds histogram' in text
        assert 'latency_seconds_bucket{le="0.1"} 1' in text
        assert 'latency_seconds_bucket{le="1"} 1' in text
        assert 'latency_seconds_bucket{le="+Inf"} 2' in text
        assert "latency_seconds_count 2" in text

    def test_inf_bucket_equals_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", bounds=(1.0,))
        for v in (0.5, 2.0, 3.0):
            h.observe(v)
        assert h.buckets()[-1] == (math.inf, 3) and h.count == 3

    def test_sanitize(self):
        assert sanitize("wal.fsync-seconds") == "wal_fsync_seconds"
        assert sanitize("engine.votes_in") == "engine_votes_in"
        assert sanitize("9lives") == "_9lives"


class TestTimelines:
    def test_create_vote_decide(self):
        engine = fresh_engine()
        pid = engine.create_proposal("s", make_request(2), NOW).proposal_id
        tl = engine.proposal_timeline("s", pid)
        assert tl["created_at"] == NOW
        assert tl["outcome"] is None and tl["first_vote_at"] is None

        hist = engine.metrics.histogram(DECISION_LATENCY)
        before = hist.count
        for _ in range(2):
            vote = build_vote(
                engine.get_proposal("s", pid), True, random_stub_signer(), NOW + 1
            )
            engine.ingest_votes([("s", vote)], NOW + 1)
        tl = engine.proposal_timeline("s", pid)
        assert tl["first_vote_at"] == NOW + 1
        assert tl["quorum_at"] == NOW + 1  # vote quorum = decision moment
        assert tl["decided_at"] == NOW + 1
        assert tl["outcome"] == "yes" and not tl["by_timeout"]
        assert tl["decision_latency_s"] >= 0
        assert hist.count == before + 1

    def test_timeout_outcome(self):
        engine = fresh_engine()
        pid = engine.create_proposal("s", make_request(3), NOW).proposal_id
        vote = build_vote(
            engine.get_proposal("s", pid), True, random_stub_signer(), NOW + 1
        )
        engine.ingest_votes([("s", vote)], NOW + 1)
        engine.sweep_timeouts(NOW + 200)
        tl = engine.proposal_timeline("s", pid)
        assert tl["by_timeout"] is True
        assert tl["quorum_at"] is None  # no quorum ever reached
        assert tl["outcome"] in ("yes", "no", "failed")

    def test_pre_decided_session_has_no_fabricated_latency(self):
        """A proposal that arrives already decided (vote-carrying gossip)
        stamps its outcome but neither observes nor reports a decision
        latency — the wall stamps would measure load time."""
        sender = fresh_engine()
        pid = sender.create_proposal("s", make_request(2), NOW).proposal_id
        for _ in range(2):
            vote = build_vote(
                sender.get_proposal("s", pid), True, random_stub_signer(), NOW + 1
            )
            sender.ingest_votes([("s", vote)], NOW + 1)
        decided_proposal = sender.get_proposal("s", pid)

        receiver = fresh_engine()
        hist = receiver.metrics.histogram(DECISION_LATENCY)
        before = hist.count
        receiver.process_incoming_proposal("s", decided_proposal, NOW + 2)
        tl = receiver.proposal_timeline("s", pid)
        assert tl["outcome"] == "yes" and tl["pre_decided"] is True
        assert "decision_latency_s" not in tl
        assert hist.count == before

    def test_idempotent_timeout_not_counted(self):
        """handle_consensus_timeout on an already-decided session returns
        the result idempotently and must NOT inflate the fired counter."""
        engine = fresh_engine()
        pid = engine.create_proposal("s", make_request(2), NOW).proposal_id
        for _ in range(2):
            vote = build_vote(
                engine.get_proposal("s", pid), True, random_stub_signer(), NOW + 1
            )
            engine.ingest_votes([("s", vote)], NOW + 1)
        counter = engine.metrics.counter(TIMEOUTS_FIRED_TOTAL)
        before = counter.value
        assert engine.handle_consensus_timeout("s", pid, NOW + 200) is True
        assert counter.value == before

    def test_survives_delete_scope(self):
        engine = fresh_engine()
        pid = engine.create_proposal("s", make_request(2), NOW).proposal_id
        engine.delete_scope("s")
        tl = engine.proposal_timeline("s", pid)
        assert tl is not None and tl["proposal_id"] == pid

    def test_unknown_proposal(self):
        assert fresh_engine().proposal_timeline("s", 12345) is None

    def test_wal_replay_does_not_pollute_decision_metrics(self, tmp_path):
        """Recovery replays pre-crash decisions at replay speed; they must
        not feed the decision-latency histogram or re-count as fresh
        decisions (they were made before the crash)."""
        from hashgraph_tpu import DurableEngine

        durable = DurableEngine(
            fresh_engine(), str(tmp_path / "wal"), fsync_policy="off"
        )
        pid = durable.create_proposal("s", make_request(2), NOW).proposal_id
        for _ in range(2):
            vote = build_vote(
                durable.get_proposal("s", pid), True, random_stub_signer(), NOW + 1
            )
            durable.ingest_votes([("s", vote)], NOW + 1)
        durable.close()

        restarted = DurableEngine(
            fresh_engine(), str(tmp_path / "wal"), fsync_policy="off"
        )
        hist = restarted.engine.metrics.histogram(DECISION_LATENCY)
        counter = restarted.engine.metrics.counter(DECISIONS_TOTAL)
        before_hist, before_count = hist.count, counter.value
        restarted.recover()
        assert restarted.get_consensus_result("s", pid) is True
        assert hist.count == before_hist
        assert counter.value == before_count
        tl = restarted.proposal_timeline("s", pid)
        assert tl["outcome"] == "yes" and tl["pre_decided"] is True
        assert "decision_latency_s" not in tl
        # Replay mode is OFF again: a fresh post-recovery decision counts.
        pid2 = restarted.create_proposal("s", make_request(2), NOW + 2).proposal_id
        for _ in range(2):
            vote = build_vote(
                restarted.get_proposal("s", pid2), True, random_stub_signer(), NOW + 3
            )
            restarted.ingest_votes([("s", vote)], NOW + 3)
        assert hist.count == before_hist + 1
        assert counter.value == before_count + 1
        restarted.close()

    def test_columnar_path_stamps_timeline(self):
        engine = fresh_engine(capacity=8, voter_capacity=4)
        engine.scope("s").with_threshold(1.0).initialize()
        import numpy as np

        pid = engine.create_proposal("s", make_request(2), NOW).proposal_id
        gids = np.array(
            [engine.voter_gid(bytes([i + 1]) * 20) for i in range(2)], np.int64
        )
        statuses = engine.ingest_columnar(
            "s",
            np.full(2, pid, np.int64),
            gids,
            np.ones(2, bool),
            NOW + 1,
        )
        assert int(statuses.sum()) == 0  # all OK
        tl = engine.proposal_timeline("s", pid)
        assert tl["first_vote_at"] == NOW + 1
        assert tl["outcome"] == "yes"


class TestFlightRecorder:
    def test_bounded_ring(self):
        recorder = FlightRecorder(capacity=4)
        for i in range(10):
            recorder.record("e", i=i)
        events = recorder.events()
        assert len(events) == 4
        assert [attrs["i"] for _, _, attrs in events] == [6, 7, 8, 9]

    def test_dump_jsonl(self, tmp_path):
        recorder = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        recorder.record("boom", detail="x", weird=object())
        path = recorder.dump("test-fault")
        lines = [
            json.loads(line)
            for line in open(path).read().splitlines()
        ]
        assert lines[0]["type"] == "flight_header"
        assert lines[0]["reason"] == "test-fault"
        assert lines[1]["kind"] == "boom" and lines[1]["detail"] == "x"
        assert "object object" in lines[1]["weird"]  # repr()d, not crashed

    def test_dump_throttled(self, tmp_path):
        recorder = FlightRecorder(
            capacity=8, dump_dir=str(tmp_path), min_dump_interval=3600
        )
        recorder.record("e")
        assert recorder.dump("first") is not None
        assert recorder.dump("second") is None  # throttled
        # An explicit path bypasses throttling (embedder asked).
        explicit = str(tmp_path / "explicit.jsonl")
        assert recorder.dump("third", path=explicit) == explicit

    def test_explicit_dump_does_not_consume_throttle(self, tmp_path):
        """A periodic explicit-path dump must not suppress the next real
        fault's automatic dump."""
        recorder = FlightRecorder(
            capacity=8, dump_dir=str(tmp_path), min_dump_interval=3600
        )
        recorder.record("e")
        assert recorder.dump("periodic", path=str(tmp_path / "p.jsonl"))
        assert recorder.dump("real-fault") is not None

    def test_dump_never_raises_on_unwritable_dir(self, tmp_path):
        """The dump runs on fault paths: an unwritable destination must
        yield None, never a second exception shadowing the original."""
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("file where a directory is needed")
        recorder = FlightRecorder(
            capacity=8, dump_dir=str(blocker / "sub")
        )
        recorder.record("e")
        assert recorder.dump("fault") is None

    def test_engine_fault_dumps(self, tmp_path, monkeypatch):
        engine = fresh_engine()
        pid = engine.create_proposal("s", make_request(2), NOW).proposal_id
        vote = build_vote(
            engine.get_proposal("s", pid), True, random_stub_signer(), NOW
        )
        monkeypatch.setenv("HASHGRAPH_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setattr(global_flight, "_last_dump", 0.0)

        def boom(*args, **kwargs):
            raise RuntimeError("pool died")

        monkeypatch.setattr(engine._pool, "ingest", boom)
        with pytest.raises(RuntimeError):
            engine.ingest_votes([("s", vote)], NOW + 1)
        dumps = list(tmp_path.glob("flight-*.jsonl"))
        assert dumps, "engine fault did not produce a flight dump"
        content = dumps[0].read_text()
        assert "engine.fault" in content
        assert "pool died" in content
        # The ring's recent history (the ingest attempt) is in the dump.
        assert "engine.ingest_votes" in content


class TestSidecar:
    def test_metrics_and_healthz(self):
        reg = MetricsRegistry()
        reg.counter("smoke_total").inc(2)
        sidecar = MetricsSidecar(reg, health_fn=lambda: {"ok": True, "n": 1})
        host, port = sidecar.start()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5
            ) as response:
                assert response.headers["Content-Type"].startswith("text/plain")
                text = response.read().decode()
            assert "smoke_total 2" in text
            with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=5
            ) as response:
                assert json.loads(response.read()) == {"ok": True, "n": 1}
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
            assert err.value.code == 404
        finally:
            sidecar.stop()

    def test_unhealthy_is_503(self):
        sidecar = MetricsSidecar(
            MetricsRegistry(), health_fn=lambda: {"ok": False}
        )
        host, port = sidecar.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
            assert err.value.code == 503
        finally:
            sidecar.stop()


class TestBridgeObservability:
    def test_sidecar_and_get_metrics_opcode(self):
        with BridgeServer(capacity=16, voter_capacity=8, metrics_port=0) as server:
            host, port = server.metrics_address
            with BridgeClient(*server.address) as client:
                peer, _ = client.add_peer()
                pid, _ = client.create_proposal(
                    peer, "obs", NOW, "p", b"", 2, 100
                )
                client.cast_vote(peer, "obs", pid, True, NOW + 1)
                with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=5
                ) as response:
                    text = response.read().decode()
                for family in (
                    "hashgraph_decision_latency_seconds_bucket",
                    "hashgraph_ingest_batch_size_bucket",
                    "hashgraph_live_proposals",
                    "bridge_requests_total",
                ):
                    assert family in text, family
                with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5
                ) as response:
                    health = json.loads(response.read())
                assert health["ok"] is True and health["peers"] >= 1
                # The identical exposition over the bridge wire.
                wire_text = client.get_metrics()
                assert "hashgraph_decision_latency_seconds_bucket" in wire_text
                assert "bridge_requests_total" in wire_text
        # Sidecar is down after stop().
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=1)

    def test_sidecar_bind_failure_releases_bridge_listener(self):
        """A metrics-port conflict in start() must not leave a half-started
        server holding the bridge port (with-statement never reaches
        stop() when __enter__ raises)."""
        import socket

        blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken_port = blocker.getsockname()[1]
        try:
            server = BridgeServer(
                capacity=8, voter_capacity=8, metrics_port=taken_port
            )
            with pytest.raises(OSError):
                server.start()
            assert server._running is False and server._listener is None
            # The same object can start cleanly afterwards.
            server._metrics_port = 0
            server.start()
            try:
                with BridgeClient(*server.address) as client:
                    assert client.ping() >= 1
            finally:
                server.stop()
        finally:
            blocker.close()

    def test_healthz_degraded_reasons_schema(self):
        """The enriched /healthz body: 'alerts' always present; a firing
        critical rule adds machine-readable 'reasons' (rule / severity /
        details) and flips the status to 503 — the schema a load
        balancer's operator scripts against."""
        from hashgraph_tpu.obs.health import HealthMonitor

        monitor = HealthMonitor(registry=MetricsRegistry())
        with BridgeServer(
            capacity=8, voter_capacity=8, metrics_port=0,
            health_monitor=monitor,
        ) as server:
            host, port = server.metrics_address
            with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=5
            ) as response:
                healthy = json.loads(response.read())
            assert healthy["ok"] is True
            assert healthy["alerts"] == [] and "reasons" not in healthy

            monitor.note_equivocation("s", 7, b"\x01", b"\x02", b"\x09" * 20, 1)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
            assert err.value.code == 503
            degraded = json.loads(err.value.read())
            assert degraded["ok"] is False
            assert isinstance(degraded["reasons"], list) and degraded["reasons"]
            for reason in degraded["reasons"]:
                assert set(reason) == {
                    "rule", "severity", "description", "details",
                }
                assert reason["severity"] == "critical"
            # Warnings ride along in alerts without appearing in reasons.
            rules_in_alerts = {a["rule"] for a in degraded["alerts"]}
            assert "peer-faulty" in rules_in_alerts

    def test_requests_counter_advances(self):
        before = global_registry.counter("bridge_requests_total").value
        with BridgeServer(capacity=8, voter_capacity=8) as server:
            with BridgeClient(*server.address) as client:
                client.ping()
                client.ping()
        assert global_registry.counter("bridge_requests_total").value >= before + 2

    def test_dispatch_fault_dumps_flight(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HASHGRAPH_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setattr(global_flight, "_last_dump", 0.0)
        with BridgeServer(capacity=8, voter_capacity=8) as server:
            with BridgeClient(*server.address) as client:
                peer, _ = client.add_peer()

                def killed(*args, **kwargs):
                    raise RuntimeError("peer engine killed mid-run")

                server._peers[peer].engine.create_proposal = killed
                with pytest.raises(BridgeError) as err:
                    client.create_proposal(peer, "s", NOW, "p", b"", 2, 100)
                assert err.value.status == 250  # STATUS_INTERNAL
        dumps = sorted(tmp_path.glob("flight-*.jsonl"))
        assert dumps, "bridge dispatch fault did not produce a flight dump"
        content = "".join(p.read_text() for p in dumps)
        assert "bridge.dispatch_error" in content
        assert "peer engine killed mid-run" in content
        # The events leading up to the fault (the ADD_PEER and the fatal
        # dispatch) are in the ring.
        assert "bridge.op" in content
