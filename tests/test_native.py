"""Native C++ runtime: parity with the pure-Python crypto oracle.

The native library must be a drop-in: byte-identical hashes, byte-identical
deterministic signatures (RFC 6979), and the same verify verdicts/errors.
Skipped wholesale when the runtime cannot be built/loaded (it is optional).
"""

import hashlib
import os

import pytest

from hashgraph_tpu import native
from hashgraph_tpu.errors import ConsensusSchemeError
from hashgraph_tpu.signing._keccak import keccak256 as py_keccak256
from hashgraph_tpu.signing.ethereum import EthereumConsensusSigner, eip191_hash

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable"
)


def signer_with_seed(seed: int) -> EthereumConsensusSigner:
    return EthereumConsensusSigner(seed.to_bytes(32, "big"))


class TestHashing:
    @pytest.mark.parametrize("length", [0, 1, 31, 32, 135, 136, 137, 500, 1000])
    def test_keccak_parity(self, length):
        data = bytes(range(256))[:length] if length <= 256 else os.urandom(length)
        data = (data * (length // max(len(data), 1) + 1))[:length]
        assert native.keccak256(data) == py_keccak256(data)

    def test_sha256_batch(self):
        items = [os.urandom(n) for n in (0, 10, 64, 100, 300)]
        digests = native.sha256_batch(items)
        for item, digest in zip(items, digests):
            assert digest.tobytes() == hashlib.sha256(item).digest()

    def test_keccak_batch(self):
        items = [os.urandom(n) for n in (5, 200)]
        digests = native.keccak256_batch(items)
        for item, digest in zip(items, digests):
            assert digest.tobytes() == py_keccak256(item)


class TestEcdsa:
    @pytest.mark.parametrize("seed", [1, 2, 0xDEADBEEF, 2**200 + 7])
    def test_sign_determinism_matches_python(self, seed):
        """Native RFC 6979 signing must produce byte-identical signatures to
        the Python implementation (both are deterministic)."""
        signer = signer_with_seed(seed)
        payload = b"payload-%d" % seed
        native_sig = native.eth_sign(signer.private_key_bytes(), payload)
        # Force the Python path for comparison.
        from hashgraph_tpu.signing._secp256k1 import sign_recoverable

        r, s, v = sign_recoverable(eip191_hash(payload), seed)
        python_sig = (
            r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([27 + (v & 1)])
        )
        assert native_sig == python_sig

    def test_address_parity(self):
        for seed in (1, 3, 2**128 + 5):
            signer = signer_with_seed(seed)
            assert native.eth_address(signer.private_key_bytes()) == signer.identity()

    def test_verify_roundtrip_and_tamper(self):
        signer = signer_with_seed(42)
        payload = b"hello consensus"
        sig = signer.sign(payload)
        assert native.eth_verify(signer.identity(), payload, sig) == 1
        other = signer_with_seed(43)
        assert native.eth_verify(other.identity(), payload, sig) == 0
        bad = bytearray(sig)
        bad[5] ^= 0xFF
        assert native.eth_verify(signer.identity(), payload, bytes(bad)) in (0, -2)

    def test_scheme_uses_native_and_matches(self):
        """EthereumConsensusSigner routes through native when available; its
        observable behavior must be unchanged."""
        signer = signer_with_seed(77)
        payload = b"scheme-level"
        sig = signer.sign(payload)
        assert EthereumConsensusSigner.verify(signer.identity(), payload, sig)
        assert not EthereumConsensusSigner.verify(
            signer_with_seed(78).identity(), payload, sig
        )
        with pytest.raises(ConsensusSchemeError):
            EthereumConsensusSigner.verify(signer.identity(), payload, sig[:10])

    def test_verify_batch_mixed(self):
        signers = [signer_with_seed(s) for s in (10, 11, 12, 13)]
        payloads = [b"m%d" % i for i in range(4)]
        sigs = [s.sign(p) for s, p in zip(signers, payloads)]
        identities = [s.identity() for s in signers]
        # Corrupt: wrong signer for #1, short signature for #2, bad recid #3.
        identities[1] = signer_with_seed(99).identity()
        sigs[2] = sigs[2][:30]
        sigs[3] = sigs[3][:64] + bytes([99])
        results = EthereumConsensusSigner.verify_batch(identities, payloads, sigs)
        assert results[0] is True
        assert results[1] is False
        assert isinstance(results[2], ConsensusSchemeError)
        assert isinstance(results[3], ConsensusSchemeError)

    def test_glv_recover_stress(self):
        """256 random keys/payloads through the batch verifier. The recover
        scalar u2 = s·r⁻¹ mod n is effectively uniform, so this sweeps the
        GLV split across random scalars; any decomposition bug shows up as a
        wrong recovered address. Tampered copies must all flip to invalid."""
        import random

        rng = random.Random(0x61F)
        keys = [rng.getrandbits(255) | 1 for _ in range(256)]
        signers = [signer_with_seed(k) for k in keys]
        payloads = [rng.getrandbits(8 * 24).to_bytes(24, "big") for _ in keys]
        sigs = [s.sign(p) for s, p in zip(signers, payloads)]
        ids = [s.identity() for s in signers]
        res = native.eth_verify_batch(ids, payloads, sigs)
        assert res.tolist() == [1] * len(keys)
        # Flip one byte of each signature's r: verify must not return 1.
        bad = [bytes([sig[0] ^ 0x01]) + sig[1:] for sig in sigs]
        res_bad = native.eth_verify_batch(ids, payloads, bad)
        assert all(r in (0, 254) for r in res_bad.tolist())

    def test_batch_matches_scalar_loop(self):
        signers = [signer_with_seed(s) for s in range(30, 36)]
        payloads = [os.urandom(40) for _ in signers]
        sigs = [s.sign(p) for s, p in zip(signers, payloads)]
        identities = [s.identity() for s in signers]
        batch = EthereumConsensusSigner.verify_batch(identities, payloads, sigs)
        for i in range(len(signers)):
            assert batch[i] is EthereumConsensusSigner.verify(
                identities[i], payloads[i], sigs[i]
            )
