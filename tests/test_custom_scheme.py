"""Scheme-agnosticism: the service works with any signature scheme
(reference: tests/custom_scheme_tests.rs)."""

import hashlib

import pytest

from hashgraph_tpu import (
    BroadcastEventBus,
    ConsensusService,
    CreateProposalRequest,
    InMemoryConsensusStorage,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.errors import InvalidVoteSignature
from hashgraph_tpu.signing import ConsensusSignatureScheme

from common import NOW

SCOPE = "custom_scheme_scope"


class PrefixScheme(ConsensusSignatureScheme):
    """A from-scratch scheme (not the built-in stub): signature =
    sha256(b'custom:' || identity || payload)."""

    def __init__(self, identity: bytes):
        self._identity = identity

    def identity(self) -> bytes:
        return self._identity

    def sign(self, payload: bytes) -> bytes:
        return hashlib.sha256(b"custom:" + self._identity + payload).digest()

    @classmethod
    def verify(cls, identity, payload, signature) -> bool:
        return hashlib.sha256(b"custom:" + bytes(identity) + payload).digest() == signature


def make_custom_service(identity=b"peer-A" + b"\x00" * 14):
    return ConsensusService(
        InMemoryConsensusStorage(), BroadcastEventBus(), PrefixScheme(identity)
    )


def test_consensus_with_custom_scheme():
    """reference: tests/custom_scheme_tests.rs:91-136"""
    service = make_custom_service()
    request = CreateProposalRequest(
        name="Custom",
        payload=b"",
        proposal_owner=service.signer().identity(),
        expected_voters_count=3,
        expiration_timestamp=60,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal(SCOPE, request, NOW)
    service.cast_vote(SCOPE, proposal.proposal_id, True, NOW)

    peer = ConsensusService(
        service.storage(), service.event_bus(), PrefixScheme(b"peer-B" + b"\x00" * 14)
    )
    peer.cast_vote(SCOPE, proposal.proposal_id, True, NOW)
    assert service.storage().get_consensus_result(SCOPE, proposal.proposal_id) is True


def test_forged_signature_rejected_by_custom_scheme():
    """reference: tests/custom_scheme_tests.rs:139-178"""
    service = make_custom_service()
    request = CreateProposalRequest(
        name="Forged",
        payload=b"",
        proposal_owner=service.signer().identity(),
        expected_voters_count=3,
        expiration_timestamp=60,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal(SCOPE, request, NOW)
    snapshot = service.storage().get_proposal(SCOPE, proposal.proposal_id)

    voter = PrefixScheme(b"peer-V" + b"\x00" * 14)
    vote = build_vote(snapshot, True, voter, NOW)
    # Tamper with the signature so verify() returns False (hash still valid).
    vote.signature = bytes(b ^ 0xFF for b in vote.signature)

    with pytest.raises(InvalidVoteSignature):
        service.process_incoming_vote(SCOPE, vote, NOW)


def test_schemes_do_not_cross_validate():
    """A vote signed under one scheme fails under another service's scheme."""
    stub_service = ConsensusService(
        InMemoryConsensusStorage(), BroadcastEventBus(), StubConsensusSigner(b"stub-peer")
    )
    request = CreateProposalRequest(
        name="Cross",
        payload=b"",
        proposal_owner=b"stub-peer",
        expected_voters_count=3,
        expiration_timestamp=60,
        liveness_criteria_yes=True,
    )
    proposal = stub_service.create_proposal(SCOPE, request, NOW)
    snapshot = stub_service.storage().get_proposal(SCOPE, proposal.proposal_id)

    custom_voter = PrefixScheme(b"custom-peer")
    vote = build_vote(snapshot, True, custom_voter, NOW)
    with pytest.raises(InvalidVoteSignature):
        stub_service.process_incoming_vote(SCOPE, vote, NOW)
