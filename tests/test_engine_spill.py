"""Host-spill lifecycle: sessions the pool geometry cannot hold degrade to
host-backed scalar sessions with identical observable semantics.

The reference service has no capacity limits at all (reference:
src/service.rs:86-97 — unbounded sessions, any u32 expected_voters_count);
the engine's fixed pool geometry must therefore never surface as an API
error. These tests drive spilled sessions through the full lifecycle —
voting, consensus, timeout, events, stats, eviction, checkpoint — and pin
parity between a spilled engine and a scalar service."""

import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    InMemoryConsensusStorage,
    StatusCode,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.errors import ConsensusFailed, InsufficientVotesAtTimeout
from hashgraph_tpu.types import ConsensusFailedEvent, ConsensusReached

from common import NOW, random_stub_signer


def request(n=3, name="prop", exp=1000, liveness=True) -> CreateProposalRequest:
    return CreateProposalRequest(
        name=name,
        payload=b"payload",
        proposal_owner=b"owner",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


def drain(receiver):
    events = []
    while (item := receiver.try_recv()) is not None:
        events.append(item)
    return events


def tiny_engine(**kw) -> TpuConsensusEngine:
    kw.setdefault("capacity", 1)
    kw.setdefault("voter_capacity", 4)
    return TpuConsensusEngine(random_stub_signer(), **kw)


class TestSpillOnPoolExhaustion:
    def test_spilled_session_reaches_consensus(self):
        engine = tiny_engine()
        receiver = engine.event_bus().subscribe()
        engine.create_proposal("s", request(3, name="pooled"), NOW)
        pid = engine.create_proposal("s", request(3, name="spilled"), NOW).proposal_id
        assert engine.pool().free_slots == 0

        for _ in range(2):
            vote = build_vote(
                engine.get_proposal("s", pid), True, random_stub_signer(), NOW
            )
            assert engine.ingest_votes([("s", vote)], NOW)[0] == int(StatusCode.OK)
        assert engine.get_consensus_result("s", pid) is True
        assert ("s", ConsensusReached(pid, True, NOW)) in drain(receiver)

    def test_spilled_vote_after_reached_is_already_reached(self):
        engine = tiny_engine()
        engine.create_proposal("s", request(3), NOW)
        pid = engine.create_proposal("s", request(3, name="sp"), NOW).proposal_id
        receiver = engine.event_bus().subscribe()
        statuses = []
        for _ in range(3):
            vote = build_vote(
                engine.get_proposal("s", pid), True, random_stub_signer(), NOW
            )
            statuses.append(engine.ingest_votes([("s", vote)], NOW)[0])
        assert statuses == [
            int(StatusCode.OK),
            int(StatusCode.OK),
            int(StatusCode.ALREADY_REACHED),
        ]
        # The deciding vote emits, and the late vote re-emits (reference:
        # src/session.rs:246 returns the existing result -> another event).
        events = drain(receiver)
        assert events.count(("s", ConsensusReached(pid, True, NOW))) == 2

    def test_spilled_duplicate_and_cast_vote(self):
        engine = tiny_engine()
        engine.create_proposal("s", request(3), NOW)
        pid = engine.create_proposal("s", request(3, name="sp"), NOW).proposal_id
        signer = random_stub_signer()
        vote = build_vote(engine.get_proposal("s", pid), True, signer, NOW)
        assert engine.ingest_votes([("s", vote)], NOW)[0] == int(StatusCode.OK)
        dup = build_vote(engine.get_proposal("s", pid), False, signer, NOW)
        assert engine.ingest_votes([("s", dup)], NOW)[0] == int(
            StatusCode.DUPLICATE_VOTE
        )
        # cast_vote funnels through the same host path.
        engine.cast_vote("s", pid, True, NOW)
        assert engine.get_proposal("s", pid).round == 2  # gossipsub bump

    def test_mixed_batch_pooled_and_spilled(self):
        engine = tiny_engine(capacity=2)
        pids = [
            engine.create_proposal("s", request(3, name=f"p{i}"), NOW).proposal_id
            for i in range(4)  # 2 pooled + 2 spilled
        ]
        items = []
        for pid in pids:
            items.append(
                (
                    "s",
                    build_vote(
                        engine.get_proposal("s", pid), True, random_stub_signer(), NOW
                    ),
                )
            )
        statuses = engine.ingest_votes(items, NOW, pre_validated=True)
        assert list(statuses) == [int(StatusCode.OK)] * 4
        assert engine.get_scope_stats("s").total_sessions == 4
        for pid in pids:
            assert engine.get_consensus_result("s", pid) is None  # 1 of 3 votes
            assert len(engine.get_proposal("s", pid).votes) == 1

    def test_mixed_batch_event_arrival_order(self):
        # Proposals with n=1 decide on their single vote; batch order is
        # pooled A (idx 0), spilled B (idx 1), pooled C (idx 2). Events must
        # come out A, B, C — per-vote arrival order across substrates.
        engine = tiny_engine(capacity=2)
        pids = [
            engine.create_proposal("s", request(1, name=f"p{i}"), NOW).proposal_id
            for i in range(3)  # p0, p1 pooled; p2 spilled
        ]
        receiver = engine.event_bus().subscribe()
        order = [pids[0], pids[2], pids[1]]  # pooled, spilled, pooled
        items = [
            (
                "s",
                build_vote(
                    engine.get_proposal("s", pid), True, random_stub_signer(), NOW
                ),
            )
            for pid in order
        ]
        statuses = engine.ingest_votes(items, NOW, pre_validated=True)
        assert list(statuses) == [int(StatusCode.OK)] * 3
        emitted = [e.proposal_id for _, e in drain(receiver)]
        assert emitted == order


class TestSpillOnVoterCapacity:
    def test_oversized_voter_count_spills(self):
        engine = tiny_engine(capacity=8, voter_capacity=4)
        # 9 expected voters > 4 lanes: must not error (reference accepts any
        # u32 n), runs host-backed instead.
        pid = engine.create_proposal("s", request(9), NOW).proposal_id
        assert engine.pool().allocated_slots == 0
        signers = [random_stub_signer() for _ in range(7)]
        for signer in signers[:6]:
            vote = build_vote(engine.get_proposal("s", pid), True, signer, NOW)
            assert engine.ingest_votes([("s", vote)], NOW)[0] == int(StatusCode.OK)
        # ceil(9 * 2/3) = 6 YES with 3 silent -> quorum gate still blocked
        # pre-timeout (total 6 < required 6? no: 6 >= 6, yes_w=6 > no_w=0).
        assert engine.get_consensus_result("s", pid) is True

    def test_incoming_proposal_with_oversized_chain_spills(self):
        # Build a 5-vote chain on a scalar-capable engine, ship it to an
        # engine whose pool has only 4 lanes: it must load host-backed.
        origin = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        pid = origin.create_proposal("s", request(7), NOW).proposal_id
        for _ in range(5):
            vote = build_vote(
                origin.get_proposal("s", pid), True, random_stub_signer(), NOW
            )
            origin.ingest_votes([("s", vote)], NOW)
        wire_proposal = origin.get_proposal("s", pid)

        receiver_engine = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=4
        )
        receiver_engine.process_incoming_proposal("s", wire_proposal, NOW)
        assert receiver_engine.pool().allocated_slots == 0
        # 5 YES of 7, req = ceil(14/3) = 5: reached YES during replay.
        assert receiver_engine.get_consensus_result("s", pid) is True


class TestSpilledTimeouts:
    def test_spilled_timeout_reaches_by_liveness(self):
        engine = tiny_engine()
        engine.create_proposal("s", request(3), NOW)
        pid = engine.create_proposal(
            "s", request(5, name="sp", exp=50), NOW
        ).proposal_id
        vote = build_vote(
            engine.get_proposal("s", pid), True, random_stub_signer(), NOW
        )
        engine.ingest_votes([("s", vote)], NOW)
        # Timeout: quorum gate uses n; 1 YES + 4 silent liveness-YES -> True.
        assert engine.handle_consensus_timeout("s", pid, NOW + 100) is True
        # Idempotent re-fire.
        assert engine.handle_consensus_timeout("s", pid, NOW + 200) is True

    def test_spilled_timeout_fails_and_raises(self):
        # Threshold 1.0 with a 1-YES/1-NO split cannot decide even with
        # silent weighting, so the timeout fails the session.
        signers = [random_stub_signer() for _ in range(2)]
        engine2 = tiny_engine()
        engine2.scope("x").with_threshold(1.0).initialize()
        engine2.create_proposal("x", request(3), NOW)
        pid2 = engine2.create_proposal(
            "x", request(4, name="sp", exp=50, liveness=False), NOW
        ).proposal_id
        receiver2 = engine2.event_bus().subscribe()
        for i, signer in enumerate(signers):
            v = build_vote(engine2.get_proposal("x", pid2), i == 0, signer, NOW)
            engine2.ingest_votes([("x", v)], NOW)
        with pytest.raises(InsufficientVotesAtTimeout):
            engine2.handle_consensus_timeout("x", pid2, NOW + 100)
        assert ("x", ConsensusFailedEvent(pid2, NOW + 100)) in drain(receiver2)
        with pytest.raises(ConsensusFailed):
            engine2.get_consensus_result("x", pid2)

    def test_sweep_covers_spilled_sessions(self):
        engine = tiny_engine()
        pid_pooled = engine.create_proposal(
            "s", request(5, name="pooled", exp=50), NOW
        ).proposal_id
        pid_spilled = engine.create_proposal(
            "s", request(5, name="spilled", exp=50), NOW
        ).proposal_id
        for pid in (pid_pooled, pid_spilled):
            vote = build_vote(
                engine.get_proposal("s", pid), True, random_stub_signer(), NOW
            )
            engine.ingest_votes([("s", vote)], NOW)
        swept = engine.sweep_timeouts(NOW + 100)
        assert ("s", pid_pooled, True) in swept
        assert ("s", pid_spilled, True) in swept


class TestSpilledLifecycle:
    def test_eviction_frees_slot_for_newcomer(self):
        # Eviction runs before allocation: with a 1-slot pool and a 1-session
        # scope cap, each newer proposal evicts the older AND takes its
        # device slot — it must not strand on the host path.
        engine = tiny_engine(capacity=1, max_sessions_per_scope=1)
        pid1 = engine.create_proposal("s", request(3, name="p1"), NOW).proposal_id
        pid2 = engine.create_proposal("s", request(3, name="p2"), NOW + 1).proposal_id
        assert engine.get_scope_stats("s").total_sessions == 1
        assert engine.pool().allocated_slots == 1  # p2 is pooled, not spilled
        with pytest.raises(Exception):
            engine.get_proposal("s", pid1)
        assert engine.get_proposal("s", pid2).name == "p2"

    def test_newcomer_losing_lru_tie_is_dropped(self):
        # created_at tie: incumbents win, the newcomer is never tracked
        # (insert-then-trim parity with the reference's stable sort).
        engine = tiny_engine(capacity=4, max_sessions_per_scope=1)
        pid1 = engine.create_proposal("s", request(3, name="p1"), NOW).proposal_id
        pid2 = engine.create_proposal("s", request(3, name="p2"), NOW).proposal_id
        assert engine.get_scope_stats("s").total_sessions == 1
        assert engine.get_proposal("s", pid1).name == "p1"
        with pytest.raises(Exception):
            engine.get_proposal("s", pid2)
        assert engine.pool().allocated_slots == 1

    def test_eviction_and_delete_scope_with_spills(self):
        engine = tiny_engine(max_sessions_per_scope=2)
        pids = [
            engine.create_proposal("s", request(3, name=f"p{i}"), NOW + i).proposal_id
            for i in range(4)
        ]
        assert engine.get_scope_stats("s").total_sessions == 2
        engine.delete_scope("s")
        assert engine.get_scope_stats("s").total_sessions == 0
        assert engine.pool().free_slots == engine.pool().capacity
        assert pids  # ids were all distinct

    def test_checkpoint_roundtrip_with_spilled_session(self):
        engine = tiny_engine()
        pid_pooled = engine.create_proposal("s", request(3, name="a"), NOW).proposal_id
        pid_spilled = engine.create_proposal("s", request(3, name="b"), NOW).proposal_id
        vote = build_vote(
            engine.get_proposal("s", pid_spilled), True, random_stub_signer(), NOW
        )
        engine.ingest_votes([("s", vote)], NOW)

        storage = InMemoryConsensusStorage()
        assert engine.save_to_storage(storage) == 2

        # Restore into a roomy engine: the spilled session becomes pooled.
        restored = TpuConsensusEngine(
            random_stub_signer(), capacity=8, voter_capacity=8
        )
        assert restored.load_from_storage(storage) == 2
        assert restored.pool().allocated_slots == 2
        assert restored.get_proposal("s", pid_pooled).name == "a"
        assert len(restored.get_proposal("s", pid_spilled).votes) == 1

        # Restore into a too-small engine: sessions spill, nothing raises
        # (previously a mid-restore VoterCapacityExceeded abort).
        cramped = TpuConsensusEngine(
            random_stub_signer(), capacity=1, voter_capacity=8
        )
        assert cramped.load_from_storage(storage) == 2
        assert cramped.get_scope_stats("s").total_sessions == 2

    def test_export_session_of_spilled(self):
        engine = tiny_engine()
        engine.create_proposal("s", request(3), NOW)
        pid = engine.create_proposal("s", request(3, name="sp"), NOW).proposal_id
        engine.cast_vote("s", pid, True, NOW)
        session = engine.export_session("s", pid)
        assert session.state.is_active
        assert len(session.votes) == 1
        assert session.proposal.round == 2
