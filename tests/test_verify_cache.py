"""VerifiedVoteCache: bounds, LRU policy, negative verdicts, and the
engine integration (in-batch dedup, scalar-path consultation, poisoning
resistance). Tier-1 fast — stub signatures only."""

import threading

import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine, VerifiedVoteCache
from hashgraph_tpu.engine.verify_cache import MISS, _ENTRY_OVERHEAD
from hashgraph_tpu.errors import ConsensusSchemeError, StatusCode

from common import NOW

OK = int(StatusCode.OK)


class CountingSigner(StubConsensusSigner):
    """Stub scheme that counts class-level verify calls (verify_batch
    delegates to verify via the base-class default, so one counter covers
    both entry points)."""

    calls = 0

    @classmethod
    def verify(cls, identity, payload, signature):
        cls.calls += 1
        return super().verify(identity, payload, signature)


@pytest.fixture(autouse=True)
def _reset_counter():
    CountingSigner.calls = 0


def make_engine(cache="default", signer=None):
    return TpuConsensusEngine(
        signer if signer is not None else CountingSigner(b"\x77" * 20),
        capacity=32,
        voter_capacity=8,
        verify_cache=cache,
    )


def make_proposal(engine, n=6, scope="s"):
    return engine.create_proposal(
        scope,
        CreateProposalRequest(
            name="p",
            payload=b"x",
            proposal_owner=b"o",
            expected_voters_count=n,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        ),
        NOW,
    )


class TestCacheBounds:
    def test_roundtrip_and_miss(self):
        cache = VerifiedVoteCache(max_entries=4)
        assert cache.get(b"k1") is MISS
        cache.put(b"k1", True)
        assert cache.get(b"k1") is True
        err = ConsensusSchemeError.verify("bad")
        cache.put(b"k2", err)
        assert cache.get(b"k2") is err
        cache.put(b"k3", False)
        assert cache.get(b"k3") is False

    def test_entry_cap_evicts_lru(self):
        cache = VerifiedVoteCache(max_entries=3)
        for k in (b"a", b"b", b"c"):
            cache.put(k, True)
        cache.get(b"a")  # refresh: "b" becomes the LRU victim
        cache.put(b"d", True)
        assert len(cache) == 3
        assert cache.get(b"b") is MISS
        assert cache.get(b"a") is True

    def test_byte_cap_evicts(self):
        per_entry = 8 + _ENTRY_OVERHEAD
        cache = VerifiedVoteCache(max_entries=1000, max_bytes=3 * per_entry)
        for i in range(10):
            cache.put(b"key%05d" % i, True)
        assert len(cache) <= 3
        assert cache.bytes_used <= 3 * per_entry
        # Newest survives.
        assert cache.get(b"key00009" ) is True

    def test_overwrite_does_not_leak_bytes(self):
        cache = VerifiedVoteCache(max_entries=8)
        for _ in range(100):
            cache.put(b"same-key", True)
        assert len(cache) == 1
        assert cache.bytes_used == 8 + _ENTRY_OVERHEAD

    def test_invalid_bounds_rejected(self):
        with pytest.raises(ValueError):
            VerifiedVoteCache(max_entries=0)
        with pytest.raises(ValueError):
            VerifiedVoteCache(max_bytes=0)

    def test_clear_and_stats(self):
        cache = VerifiedVoteCache(max_entries=8, max_bytes=10_000)
        cache.put(b"k", True)
        stats = cache.stats()
        assert stats["entries"] == 1 and stats["max_bytes"] == 10_000
        cache.clear()
        assert len(cache) == 0 and cache.bytes_used == 0

    def test_concurrent_put_get_stays_bounded(self):
        cache = VerifiedVoteCache(max_entries=64)
        errors = []

        def worker(seed):
            try:
                for i in range(500):
                    cache.put(b"%d-%d" % (seed, i % 100), bool(i % 2))
                    cache.get(b"%d-%d" % ((seed + 1) % 4, i % 100))
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(cache) <= 64


class TestAdmissionKey:
    def test_key_is_fixed_size_framed_digest(self):
        k = VerifiedVoteCache.key(b"payload", b"sig", b"tag")
        assert len(k) == 32  # digest form: flat key size regardless of inputs
        assert k == VerifiedVoteCache.key(b"payload", b"sig", b"tag")
        assert k != VerifiedVoteCache.key(b"payload", b"sig", b"other")
        assert k != VerifiedVoteCache.key(b"other", b"sig", b"tag")
        assert k != VerifiedVoteCache.key(b"payload", b"other", b"tag")
        # Length framing: shifting bytes across a component boundary must
        # change the key — plain concatenation would not.
        assert VerifiedVoteCache.key(b"b", b"", b"a") != VerifiedVoteCache.key(
            b"ab", b"", b""
        )
        assert VerifiedVoteCache.key(b"a", b"b", b"") != VerifiedVoteCache.key(
            b"", b"ab", b""
        )


class TestEngineIntegration:
    def test_redelivered_vote_verified_once(self):
        engine = make_engine()
        proposal = make_proposal(engine)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        CountingSigner.calls = 0
        engine.process_incoming_vote("s", vote.clone(), NOW + 2)
        assert CountingSigner.calls == 1
        # Redelivery: admission is a cache hit; the duplicate rejection
        # still fires, so statuses are unchanged from the uncached flow.
        [code] = engine.ingest_votes([("s", vote.clone())], NOW + 3)
        assert CountingSigner.calls == 1
        assert int(code) != OK

    def test_in_batch_dedup_single_verify(self):
        engine = make_engine()
        proposal = make_proposal(engine)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        CountingSigner.calls = 0
        statuses = engine.ingest_votes(
            [("s", vote.clone()) for _ in range(5)], NOW + 2
        )
        assert CountingSigner.calls == 1
        # First instance applies, the rest are duplicates — same as uncached.
        assert int(statuses[0]) == OK
        assert all(int(s) != OK for s in statuses[1:])

    def test_negative_verdict_cached(self):
        engine = make_engine()
        proposal = make_proposal(engine)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        vote.signature = b"\x00" * 32  # wrong, but structurally present
        CountingSigner.calls = 0
        for _ in range(3):
            [code] = engine.ingest_votes([("s", vote.clone())], NOW + 2)
            assert int(code) == int(StatusCode.INVALID_VOTE_SIGNATURE)
        assert CountingSigner.calls == 1

    def test_forged_signature_cannot_poison_good_vote(self):
        engine = make_engine()
        proposal = make_proposal(engine)
        good = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        forged = good.clone()
        forged.signature = b"\xff" * 32
        [code] = engine.ingest_votes([("s", forged)], NOW + 2)
        assert int(code) == int(StatusCode.INVALID_VOTE_SIGNATURE)
        # The forged delivery must not have poisoned (or pre-seeded a
        # rejection for) the honestly signed vote.
        [code] = engine.ingest_votes([("s", good)], NOW + 2)
        assert int(code) == OK

    def test_collision_twin_cannot_inherit_cached_verdict(self):
        """compute_vote_hash concatenates parent_hash/received_hash with
        no length framing, so swapping bytes between those fields yields
        a DIFFERENT signing payload with the SAME vote hash. The
        admission key is a digest of the signed bytes, so the
        never-signed twin is a cache miss and is rejected exactly as the
        uncached scheme.verify would — a (vote_hash, signature) key
        would have served it the honest vote's cached True, admitting
        forged chain-linkage fields."""
        from hashgraph_tpu.protocol import compute_vote_hash

        engine = make_engine()
        proposal = make_proposal(engine)
        first = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        chain = proposal.clone()
        chain.votes.append(first.clone())
        honest = build_vote(chain, True, CountingSigner(b"\x02" * 20), NOW + 2)
        # Exactly one of the two adjacent chain-link fields is non-empty:
        # the unframed concatenation cannot tell which side owns the bytes.
        assert honest.parent_hash == b""
        assert honest.received_hash == first.vote_hash
        crafted = honest.clone()
        crafted.parent_hash = honest.received_hash
        crafted.received_hash = honest.parent_hash
        assert compute_vote_hash(crafted) == compute_vote_hash(honest)
        assert crafted.signing_payload() != honest.signing_payload()
        statuses = engine.ingest_votes(
            [("s", first.clone()), ("s", honest.clone())], NOW + 3
        )
        assert [int(s) for s in statuses] == [OK, OK]  # honest verdict cached
        [code] = engine.ingest_votes([("s", crafted)], NOW + 3)
        assert int(code) == int(StatusCode.INVALID_VOTE_SIGNATURE)

    def test_tampered_hash_field_not_cached(self):
        engine = make_engine()
        proposal = make_proposal(engine)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        bad = vote.clone()
        bad.vote_hash = b"\x01" * 32  # mismatched embedded hash
        [code] = engine.ingest_votes([("s", bad)], NOW + 2)
        assert int(code) == int(StatusCode.INVALID_VOTE_HASH)
        assert len(engine.verify_cache()) == 0  # nothing cached for it
        [code] = engine.ingest_votes([("s", vote.clone())], NOW + 2)
        assert int(code) == OK

    def test_unknown_string_sentinel_rejected(self):
        with pytest.raises(ValueError):
            make_engine("shared")  # BridgeServer's sentinel, not the engine's
        from hashgraph_tpu.bridge.server import BridgeServer

        with pytest.raises(ValueError):
            BridgeServer(verify_cache="default")  # and vice versa

    def test_cache_disabled_statuses_identical(self):
        on, off = make_engine("default"), make_engine(None)
        assert off.verify_cache() is None
        votes_on, votes_off = [], []
        for engine, out in ((on, votes_on), (off, votes_off)):
            proposal = make_proposal(engine)
            chain = proposal.clone()
            for i in range(4):
                signer = CountingSigner(bytes([i + 1]) * 20)
                chain.votes.append(build_vote(chain, True, signer, NOW + i))
            batch = [("s", v.clone()) for v in chain.votes]
            # Deliver twice: growth then redelivery.
            out.append([int(s) for s in engine.ingest_votes(batch, NOW + 9)])
            out.append([int(s) for s in engine.ingest_votes(batch, NOW + 9)])
        assert votes_on == votes_off

    def test_shared_cache_across_engines(self):
        shared = VerifiedVoteCache()
        a = make_engine(shared)
        b = make_engine(shared, signer=CountingSigner(b"\x78" * 20))
        proposal = make_proposal(a)
        wire = proposal.encode()
        from hashgraph_tpu.wire import Proposal

        b.process_incoming_proposal("s", Proposal.decode(wire), NOW)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        CountingSigner.calls = 0
        a.process_incoming_vote("s", vote.clone(), NOW + 2)
        b.process_incoming_vote("s", vote.clone(), NOW + 2)
        # Second engine reuses the first's verdict: one process-wide verify.
        assert CountingSigner.calls == 1

    def test_shared_cache_isolates_schemes(self):
        """Admission keys are scheme-tagged: one shared cache serving
        engines with different signature schemes never cross-serves a
        verdict (scheme A's True is not scheme B's)."""

        class RejectingSigner(StubConsensusSigner):
            @classmethod
            def verify(cls, identity, payload, signature):
                return False

        shared = VerifiedVoteCache()
        accepting = make_engine(shared)
        rejecting = make_engine(shared, signer=RejectingSigner(b"\x79" * 20))
        proposal = make_proposal(accepting)
        from hashgraph_tpu.wire import Proposal

        rejecting.process_incoming_proposal(
            "s", Proposal.decode(proposal.encode()), NOW
        )
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        accepting.process_incoming_vote("s", vote.clone(), NOW + 2)
        assert len(shared) >= 1  # verdict cached under the stub scheme tag
        [code] = rejecting.ingest_votes([("s", vote.clone())], NOW + 2)
        assert int(code) == int(StatusCode.INVALID_VOTE_SIGNATURE)

    def test_ed25519_verdict_never_cross_served_to_ethereum(self):
        """The production-scheme pair specifically: an Ed25519 engine's
        cached True for some (payload, signature) bytes must never
        satisfy an Ethereum engine's verification of the SAME bytes —
        the admission key is namespaced by an 8-byte scheme tag derived
        from the scheme type, so the two schemes occupy disjoint key
        spaces in one shared cache."""
        from hashgraph_tpu.signing import (
            Ed25519ConsensusSigner,
            EthereumConsensusSigner,
        )

        shared = VerifiedVoteCache()
        ed = TpuConsensusEngine(
            Ed25519ConsensusSigner.random(),
            capacity=8,
            voter_capacity=4,
            verify_cache=shared,
        )
        eth = TpuConsensusEngine(
            EthereumConsensusSigner.random(),
            capacity=8,
            voter_capacity=4,
            verify_cache=shared,
        )
        assert ed._verify_scheme_tag != eth._verify_scheme_tag
        # The same (payload, signature) bytes key differently per scheme,
        # so a verdict stored under the Ed25519 tag is a MISS under the
        # Ethereum tag.
        payload, sig = b"same-bytes", b"\x01" * 64
        ed_key = VerifiedVoteCache.key(payload, sig, ed._verify_scheme_tag)
        eth_key = VerifiedVoteCache.key(payload, sig, eth._verify_scheme_tag)
        assert ed_key != eth_key
        from hashgraph_tpu.engine.verify_cache import MISS

        shared.put(ed_key, True)
        assert shared.get(eth_key) is MISS

    def test_expired_proposal_batch_buys_no_crypto(self):
        """Redelivered EXPIRED chains are excluded from the batch verify
        prepass — the same zero-crypto fail-fast the scalar path has."""
        sender = make_engine()
        proposal = make_proposal(sender, scope="src")
        chain = proposal.clone()
        for i in range(3):
            signer = CountingSigner(bytes([i + 1]) * 20)
            chain.votes.append(build_vote(chain, True, signer, NOW + i))
        receiver = make_engine()
        CountingSigner.calls = 0
        late = proposal.expiration_timestamp + 1
        statuses = receiver.ingest_proposals([("s", chain.clone())], late)
        assert [int(s) for s in statuses] == [int(StatusCode.PROPOSAL_EXPIRED)]
        assert CountingSigner.calls == 0
        assert len(receiver.verify_cache()) == 0

    def test_ingest_proposals_dedups_across_chains(self):
        """The same signed votes appearing in many chains of one batch
        collapse to one verify item each."""
        sender = make_engine()
        proposal = make_proposal(sender, scope="src")
        chain = proposal.clone()
        for i in range(3):
            signer = CountingSigner(bytes([i + 1]) * 20)
            chain.votes.append(build_vote(chain, True, signer, NOW + i))
        receiver = make_engine()
        # Two distinct scopes carry the identical chain: 3 unique votes.
        CountingSigner.calls = 0
        statuses = receiver.ingest_proposals(
            [("a", chain.clone()), ("b", chain.clone())], NOW + 10
        )
        assert [int(s) for s in statuses] == [OK, OK]
        assert CountingSigner.calls == 3

    def test_redelivered_proposal_skips_all_verification(self):
        receiver = make_engine()
        sender = make_engine()
        proposal = make_proposal(sender, scope="src")
        chain = proposal.clone()
        for i in range(3):
            signer = CountingSigner(bytes([i + 1]) * 20)
            chain.votes.append(build_vote(chain, True, signer, NOW + i))
        assert [int(s) for s in receiver.ingest_proposals(
            [("s", chain.clone())], NOW + 10
        )] == [OK]
        CountingSigner.calls = 0
        # Redelivery of a registered pid: settled before any crypto.
        statuses = receiver.ingest_proposals([("s", chain.clone())], NOW + 11)
        assert [int(s) for s in statuses] == [
            int(StatusCode.PROPOSAL_ALREADY_EXIST)
        ]
        assert CountingSigner.calls == 0

    def test_metrics_families_exposed(self):
        from hashgraph_tpu.obs import registry

        engine = make_engine()
        proposal = make_proposal(engine)
        vote = build_vote(proposal, True, CountingSigner(b"\x01" * 20), NOW + 1)
        engine.process_incoming_vote("s", vote.clone(), NOW + 2)
        engine.ingest_votes([("s", vote.clone())], NOW + 3)
        text = registry.render_prometheus()
        for family in (
            "hashgraph_verify_cache_hits_total",
            "hashgraph_verify_cache_misses_total",
            "hashgraph_verify_cache_negative_hits_total",
            "hashgraph_verify_cache_evictions_total",
            "hashgraph_chain_suffix_length",
        ):
            assert family in text, family
        snap = registry.snapshot()
        assert snap["counters"]["hashgraph_verify_cache_hits_total"] >= 1
