"""State sync end to end: snapshot format, batched chain verification,
bridge sync opcodes, CatchUpClient (install + tail + resume), adversarial
sources, and fleet catch_up_shard.

Stub signers keep the suite fast (the scheme-independent machinery is
under test); the scheme conformance suite already pins real crypto, and
``bench.py catchup`` / ``make catchup-smoke`` exercise real signatures
end to end.
"""

import hashlib
import os

import pytest

from hashgraph_tpu import (
    ConsensusState,
    CreateProposalRequest,
    StatusCode,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.bridge.client import BridgeClient, BridgeError
from hashgraph_tpu.bridge.server import BridgeServer
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.obs import flight_recorder, registry
from hashgraph_tpu.storage import InMemoryConsensusStorage
from hashgraph_tpu.sync import (
    CatchUpClient,
    SnapshotDecodeError,
    SnapshotDigestError,
    SyncStateError,
    SyncVerificationError,
    TailGapError,
    TailRecordError,
    build_snapshot,
    decode_snapshot,
    state_fingerprint,
    verify_sessions,
)
from hashgraph_tpu.sync.snapshot import (
    ITEM_END,
    ITEM_HEADER,
    ITEM_SESSION,
    MAGIC,
    SnapshotManifest,
    _u32,
    _u64,
    encode_frame,
    encode_session_item,
)
from hashgraph_tpu.wal import DurableEngine
from hashgraph_tpu.wal.recovery import read_tail

NOW = 1_700_000_000


def fresh_engine(identity: bytes = b"self-peer-identity--") -> TpuConsensusEngine:
    return TpuConsensusEngine(
        StubConsensusSigner(identity), capacity=64, voter_capacity=8
    )


def request(name="p", voters=5, expiry=10_000):
    return CreateProposalRequest(
        name=name, payload=b"x", proposal_owner=b"owner",
        expected_voters_count=voters, expiration_timestamp=expiry,
        liveness_criteria_yes=True,
    )


def grow_history(engine, scope="s", proposals=4, voters=3, now=NOW):
    """Create proposals and vote on them with distinct remote signers."""
    signers = [StubConsensusSigner(os.urandom(20)) for _ in range(voters)]
    out = engine.create_proposals(scope, [request(f"p{i}") for i in range(proposals)], now)
    for p in out:
        for s in signers:
            vote = build_vote(engine.get_proposal(scope, p.proposal_id), True, s, now + 1)
            engine.ingest_votes([(scope, vote)], now + 1, pre_validated=True)
    return out


# ── Snapshot format ────────────────────────────────────────────────────


def test_snapshot_round_trip_fingerprint_equality(tmp_path):
    durable = DurableEngine(fresh_engine(), str(tmp_path / "wal"))
    grow_history(durable, proposals=5, voters=2)
    durable.scope("cfg-scope").with_threshold(0.75).initialize()
    path = str(tmp_path / "snap.bin")
    manifest = build_snapshot(durable, path, chunk_bytes=256)
    assert manifest.watermark == durable.wal.last_lsn
    assert manifest.session_count == 5
    assert manifest.chunk_count == -(-manifest.total_bytes // 256)
    data = open(path, "rb").read()
    assert len(data) == manifest.total_bytes
    for i, digest in enumerate(manifest.digests):
        chunk = data[i * 256 : (i + 1) * 256]
        assert hashlib.sha256(chunk).digest() == digest
    watermark, sessions, configs = decode_snapshot(
        data[i : i + 256] for i in range(0, len(data), 256)
    )
    assert watermark == manifest.watermark
    assert len(sessions) == 5 and len(configs) == 1
    joiner = fresh_engine()
    storage = InMemoryConsensusStorage()
    for scope, config in configs:
        # Configs set explicitly too: load_from_storage only walks scopes
        # holding sessions, and "cfg-scope" has none (the CatchUpClient
        # install does the same).
        storage.set_scope_config(scope, config)
        joiner.set_scope_config(scope, config)
    for scope, session in sessions:
        storage.save_session(scope, session)
    joiner.load_from_storage(storage)
    assert state_fingerprint(joiner) == state_fingerprint(durable)
    durable.close()


def test_snapshot_preserves_tallies_and_states(tmp_path):
    """Columnar tallies and terminal states survive the round trip —
    state a chain replay could NOT reconstruct (the reason install is
    load_from_storage, not re-delivery)."""
    import numpy as np

    engine = fresh_engine()
    (p,) = engine.create_proposals("s", [request(voters=4)], NOW)
    gid = engine.voter_gid(b"columnar-voter-xxxxx")
    vote = build_vote(p, True, StubConsensusSigner(b"columnar-voter-xxxxx"), NOW + 1)
    statuses = engine.ingest_columnar(
        "s", np.asarray([p.proposal_id]), np.asarray([gid]),
        np.asarray([True]), NOW + 1, wire_votes=[vote.encode()],
    )
    assert int(statuses[0]) == int(StatusCode.OK)
    durable = DurableEngine(fresh_engine(), str(tmp_path / "wal"))
    # Bare (non-durable) engines snapshot too, at watermark 0.
    path = str(tmp_path / "snap.bin")
    manifest = build_snapshot(engine, path)
    assert manifest.watermark == 0
    _, sessions, _ = decode_snapshot([open(path, "rb").read()])
    joiner = fresh_engine()
    storage = InMemoryConsensusStorage()
    for scope, session in sessions:
        storage.save_session(scope, session)
    joiner.load_from_storage(storage)
    assert state_fingerprint(joiner) == state_fingerprint(engine)
    durable.close()


def test_snapshot_decode_rejects_corruption(tmp_path):
    durable = DurableEngine(fresh_engine(), str(tmp_path / "wal"))
    grow_history(durable, proposals=2, voters=2)
    path = str(tmp_path / "snap.bin")
    build_snapshot(durable, path)
    durable.close()
    data = bytearray(open(path, "rb").read())

    with pytest.raises(SnapshotDecodeError, match="CRC"):
        flipped = bytearray(data)
        flipped[len(flipped) // 2] ^= 0xFF
        decode_snapshot([bytes(flipped)])
    with pytest.raises(SnapshotDecodeError, match="incomplete frame"):
        decode_snapshot([bytes(data[:-3])])
    with pytest.raises(SnapshotDecodeError, match="magic"):
        bad = encode_frame(ITEM_HEADER, b"NOTMAGIC" + _u32(1) + _u64(0))
        decode_snapshot([bad + bytes(data[len(bad) :])])
    with pytest.raises(SnapshotDecodeError, match="trailer"):
        # Drop the END frame entirely: count check can't pass.
        end = encode_frame(ITEM_END, _u32(2) + _u32(0))
        assert data.endswith(end)
        decode_snapshot([bytes(data[: -len(end)])])
    with pytest.raises(SnapshotDecodeError, match="claims"):
        end = encode_frame(ITEM_END, _u32(2) + _u32(0))
        wrong_end = encode_frame(ITEM_END, _u32(7) + _u32(0))
        decode_snapshot([bytes(data[: -len(end)]) + wrong_end])


# ── Batched snapshot verification ──────────────────────────────────────


def _snapshot_sessions(tmp_path, proposals=3, voters=3):
    durable = DurableEngine(fresh_engine(), str(tmp_path / "wal-v"))
    grow_history(durable, proposals=proposals, voters=voters)
    path = str(tmp_path / "verify.bin")
    build_snapshot(durable, path)
    durable.close()
    _, sessions, _ = decode_snapshot([open(path, "rb").read()])
    return sessions


def test_verify_sessions_accepts_valid_chains(tmp_path):
    sessions = _snapshot_sessions(tmp_path)
    assert verify_sessions(sessions, StubConsensusSigner) == 9


def test_verify_sessions_rejects_tampering(tmp_path):
    sessions = _snapshot_sessions(tmp_path)

    forged = [(s, sess.clone()) for s, sess in sessions]
    victim = forged[0][1].proposal.votes[0]
    victim.signature = bytes(32)
    with pytest.raises(SyncVerificationError, match="signature"):
        verify_sessions(forged, StubConsensusSigner)

    forged = [(s, sess.clone()) for s, sess in sessions]
    forged[1][1].proposal.votes[-1].vote_hash = bytes(32)
    with pytest.raises(SyncVerificationError, match="hash mismatch"):
        verify_sessions(forged, StubConsensusSigner)

    forged = [(s, sess.clone()) for s, sess in sessions]
    forged[2][1].proposal.votes[0].proposal_id ^= 1
    with pytest.raises(SyncVerificationError, match="bound to proposal"):
        verify_sessions(forged, StubConsensusSigner)

    forged = [(s, sess.clone()) for s, sess in sessions]
    chain = forged[0][1].proposal.votes
    chain[0], chain[1] = chain[1], chain[0]  # break received_hash linkage
    with pytest.raises(SyncVerificationError, match="chain invalid"):
        verify_sessions(forged, StubConsensusSigner)


def test_verify_sessions_rejects_unproducible_decided_state(tmp_path):
    """The lifecycle state byte is unsigned, but a claimed decided result
    must at least be PRODUCIBLE by the decision kernel from the verified
    participants: these sessions hold 3 unanimous-yes votes of 5 expected
    (undecided on the vote path, yes-only via the liveness timeout path),
    so a snapshot claiming they decided False is a forgery no admissible
    timing could have produced."""
    sessions = _snapshot_sessions(tmp_path)
    assert sessions[0][1].state.is_active
    forged = [(s, sess.clone()) for s, sess in sessions]
    forged[0][1].state = ConsensusState.reached(False)
    with pytest.raises(SyncVerificationError, match="producible"):
        verify_sessions(forged, StubConsensusSigner)


# ── WAL tail serving ───────────────────────────────────────────────────


def test_read_tail_budget_and_resume(tmp_path):
    durable = DurableEngine(
        fresh_engine(), str(tmp_path / "wal"), segment_bytes=512
    )
    grow_history(durable, proposals=4, voters=3)
    last = durable.wal.last_lsn
    all_records, more = read_tail(str(tmp_path / "wal"), 0, 1 << 20)
    assert not more
    assert [lsn for lsn, _, _ in all_records] == list(range(1, last + 1))
    # Tiny budget: page through, records concatenate identically.
    paged = []
    after = 0
    for _ in range(10_000):
        page, more = read_tail(str(tmp_path / "wal"), after, 64)
        paged.extend(page)
        if not page:
            break
        after = page[-1][0]
        if not more and after == last:
            break
    assert paged == all_records
    # after_lsn skips the prefix exactly.
    suffix, _ = read_tail(str(tmp_path / "wal"), last - 2, 1 << 20)
    assert [lsn for lsn, _, _ in suffix] == [last - 1, last]
    durable.close()


def test_capture_consistent_watermark_matches_state(tmp_path):
    durable = DurableEngine(fresh_engine(), str(tmp_path / "wal"))
    grow_history(durable, proposals=2, voters=2)
    seen = {}

    def capture(inner, watermark):
        seen["watermark"] = watermark
        return "done"

    assert durable.capture_consistent(capture) == "done"
    assert seen["watermark"] == durable.wal.last_lsn
    durable.close()


# ── Bridge + CatchUpClient end to end ──────────────────────────────────


@pytest.fixture
def sync_server(tmp_path):
    server = BridgeServer(
        capacity=64,
        voter_capacity=8,
        wal_dir=str(tmp_path / "server-wal"),
        wal_fsync="off",
        signer_factory=StubConsensusSigner,
    )
    with server:
        host, port = server.address
        with BridgeClient(host, port) as client:
            peer, identity = client.add_peer(os.urandom(32))
            voters = [client.add_peer(os.urandom(32))[0] for _ in range(3)]
            for p in range(3):
                pid, blob = client.create_proposal(
                    peer, "sync", NOW, f"p{p}", b"payload", 4, 3_600
                )
                for vp in voters:
                    client.process_proposal(vp, "sync", blob, NOW)
                    vote = client.cast_vote(vp, "sync", pid, True, NOW + 1)
                    client.process_vote(peer, "sync", vote, NOW + 1)
            yield {
                "server": server,
                "host": host,
                "port": port,
                "client": client,
                "peer": peer,
                "voters": voters,
                "source": server.durable_engine(identity),
            }


def test_catch_up_reaches_source_state(sync_server):
    env = sync_server
    src_fp = state_fingerprint(env["source"])
    joiner = fresh_engine(b"joiner-one-identity-")
    chunks_before = registry.counter(
        "hashgraph_sync_chunks_received_total"
    ).value
    with CatchUpClient(env["host"], env["port"], env["peer"]) as cu:
        report = cu.catch_up(joiner, max_chunk_bytes=512)
    assert report.sessions_installed == 3
    assert report.votes_verified == 9
    assert state_fingerprint(joiner) == src_fp
    assert (
        registry.counter("hashgraph_sync_chunks_received_total").value
        > chunks_before
    )
    kinds = [kind for _, kind, _ in flight_recorder.events()]
    assert "sync.catchup" in kinds


def test_full_replay_matches_snapshot_install(sync_server):
    env = sync_server
    src_fp = state_fingerprint(env["source"])
    replayer = fresh_engine(b"joiner-two-identity-")
    with CatchUpClient(env["host"], env["port"], env["peer"]) as cu:
        report = cu.full_replay(replayer)
    assert report.tail_records > 0
    assert state_fingerprint(replayer) == src_fp


def test_catch_up_then_tail_resume_after_new_traffic(sync_server):
    env = sync_server
    joiner = fresh_engine(b"joiner-res-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    cu.catch_up(joiner)
    cu.close()
    # Source moves on (new proposal + votes); resume tails ONLY the new
    # records — no chunk re-download, no re-install.
    client, peer = env["client"], env["peer"]
    pid, blob = client.create_proposal(peer, "sync", NOW + 2, "late", b"z", 4, 3_600)
    vp = env["voters"][0]
    client.process_proposal(vp, "sync", blob, NOW + 2)
    vote = client.cast_vote(vp, "sync", pid, True, NOW + 3)
    client.process_vote(peer, "sync", vote, NOW + 3)
    with CatchUpClient(
        env["host"], env["port"], env["peer"], state=cu.state
    ) as cu2:
        report = cu2.catch_up(joiner)
    assert report.resumed
    assert report.chunks_fetched == 0 and report.sessions_installed == 0
    assert report.tail_records > 0
    assert state_fingerprint(joiner) == state_fingerprint(env["source"])


def test_interrupted_chunk_download_resumes(sync_server):
    env = sync_server
    joiner = fresh_engine(b"joiner-int-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    manifest = cu._bridge.sync_manifest(env["peer"], 256)
    assert manifest["chunk_count"] > 1
    cu.state.manifest = manifest
    cu.state.chunks[0] = cu._bridge.sync_chunk(
        env["peer"], manifest["snapshot_id"], 0
    )
    cu.close()  # connection drops mid-transfer
    with CatchUpClient(
        env["host"], env["port"], env["peer"], state=cu.state
    ) as cu2:
        report = cu2.catch_up(joiner, max_chunk_bytes=256)
    assert report.resumed
    assert report.chunks_fetched == manifest["chunk_count"] - 1
    assert state_fingerprint(joiner) == state_fingerprint(env["source"])


def test_corrupted_chunk_is_typed_error_with_no_partial_install(sync_server):
    env = sync_server
    joiner = fresh_engine(b"joiner-cor-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    real_chunk = cu._bridge.sync_chunk

    def corrupt(peer, snapshot_id, index):
        data = bytearray(real_chunk(peer, snapshot_id, index))
        data[0] ^= 0xFF
        return bytes(data)

    cu._bridge.sync_chunk = corrupt
    with pytest.raises(SnapshotDigestError):
        cu.catch_up(joiner)
    cu.close()
    assert joiner.occupancy()["live_sessions"] == 0  # nothing installed


def test_hostile_snapshot_verification_and_trust_escape_hatch(sync_server):
    """A source serving validly-framed but badly-signed sessions: verify
    refuses (typed, no install); trust_snapshot installs anyway."""
    env = sync_server
    server, peer = env["server"], env["peer"]
    with CatchUpClient(env["host"], env["port"], peer) as cu0:
        cu0._bridge.sync_manifest(peer, 0)  # populate the server's cache
    cached_manifest, path = server._sync_cache[peer]
    _, sessions, configs = decode_snapshot([open(path, "rb").read()])
    sessions[0][1].proposal.votes[0].signature = bytes(32)  # forge
    frames = [encode_frame(ITEM_HEADER, MAGIC + _u32(1) + _u64(cached_manifest.watermark))]
    frames.extend(
        encode_frame(ITEM_SESSION, encode_session_item(s, sess))
        for s, sess in sessions
    )
    frames.append(encode_frame(ITEM_END, _u32(len(sessions)) + _u32(0)))
    hostile = b"".join(frames)
    with open(path, "wb") as fh:
        fh.write(hostile)
    server._sync_cache[peer] = (
        SnapshotManifest(
            snapshot_id=cached_manifest.snapshot_id,
            watermark=cached_manifest.watermark,
            total_bytes=len(hostile),
            chunk_bytes=cached_manifest.chunk_bytes,
            session_count=len(sessions),
            config_count=0,
            digests=(hashlib.sha256(hostile).digest(),),
        ),
        path,
    )
    joiner = fresh_engine(b"joiner-bad-identity-")
    with CatchUpClient(env["host"], env["port"], peer) as cu:
        with pytest.raises(SyncVerificationError, match="signature"):
            cu.catch_up(joiner)
    assert joiner.occupancy()["live_sessions"] == 0
    # Operator-trusted source: same bytes install without crypto.
    trusting = fresh_engine(b"joiner-tru-identity-")
    with CatchUpClient(env["host"], env["port"], peer) as cu:
        report = cu.catch_up(trusting, trust_snapshot=True)
    assert report.votes_verified == 0
    assert report.sessions_installed == len(sessions)
    assert trusting.occupancy()["live_sessions"] == len(sessions)


def test_watermark_tail_disagreement_is_typed_error(sync_server):
    """A snapshot whose watermark the served tail no longer reaches back
    to (source compacted past it) must fail typed, not apply a gap."""
    env = sync_server
    joiner = fresh_engine(b"joiner-gap-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    cu.catch_up(joiner)
    cu.close()
    source = env["source"]
    # Source moves on AND checkpoints+compacts: the joiner's resume
    # position predates the surviving log.
    client, peer = env["client"], env["peer"]
    pid, blob = client.create_proposal(peer, "sync", NOW + 5, "post", b"z", 4, 3_600)
    source.checkpoint(InMemoryConsensusStorage(), compact=True)
    stale = fresh_engine(b"joiner-stl-identity-")
    with CatchUpClient(
        env["host"], env["port"], env["peer"], state=cu.state
    ) as cu2:
        with pytest.raises(TailGapError):
            cu2.catch_up(joiner)
    # Full replay of a compacted source is impossible for the same
    # reason — the typed error is the "you need a snapshot" signal.
    with CatchUpClient(env["host"], env["port"], env["peer"]) as cu3:
        with pytest.raises(TailGapError):
            cu3.full_replay(stale)


def test_forked_tail_suffix_settles_via_fork_path(sync_server):
    """A tail carrying a forked chain redelivery must settle through the
    engine's existing fork handling (PROPOSAL_ALREADY_EXIST, nothing
    installed over the accepted chain), landing the joiner on the
    source's exact state."""
    env = sync_server
    source = env["source"]
    joiner = fresh_engine(b"joiner-frk-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    cu.catch_up(joiner)
    # A forked redelivery reaches the SOURCE after the snapshot: same
    # prefix, divergent last vote by a different signer. The source logs
    # it (log-before-apply) and settles it as a redelivery; the tail
    # must make the joiner do exactly the same.
    reached = source.get_reached_proposals("sync")
    any_pid = reached[0][0].proposal_id
    base = source.export_session("sync", any_pid).proposal
    forked = base.clone()
    outsider = StubConsensusSigner(b"forking-outsider-xxx")
    alt = build_vote(forked, False, outsider, NOW + 1)
    forked.votes[-1] = alt  # divergent tail at the last position
    status = source.deliver_proposal("sync", forked, NOW + 2)
    assert status == int(StatusCode.PROPOSAL_ALREADY_EXIST)
    report = cu.catch_up(joiner)  # resumes: tails the fork record
    cu.close()
    assert report.tail_records >= 1
    assert state_fingerprint(joiner) == state_fingerprint(source)
    # The accepted chain is untouched on both sides.
    assert [
        v.vote_owner for v in joiner.export_session("sync", any_pid).proposal.votes
    ] == [v.vote_owner for v in base.votes]


def test_stale_retry_mid_download_restarts_cleanly(sync_server):
    """The source rebuilds its snapshot while a joiner is mid-download:
    the STALE retry must discard the dead artifact's chunks (they belong
    to different bytes/geometry) and converge on the fresh one."""
    env = sync_server
    joiner = fresh_engine(b"joiner-str-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    real_chunk = cu._bridge.sync_chunk
    fired = {}

    def chunk_then_rebuild(peer, snapshot_id, index):
        data = real_chunk(peer, snapshot_id, index)
        if not fired:
            fired["x"] = True
            env["source"].sweep_timeouts(NOW + 3)  # watermark moves...
            env["client"].sync_manifest(peer)  # ...and a rebuild lands
        return data

    cu._bridge.sync_chunk = chunk_then_rebuild
    report = cu.catch_up(joiner, max_chunk_bytes=256)
    cu.close()
    assert report.sessions_installed == 3
    assert state_fingerprint(joiner) == state_fingerprint(env["source"])


def test_tail_decode_fault_is_typed_error(sync_server):
    """A served tail record whose payload cannot decode must fail the
    catch-up typed (local crash replay tolerates and reports it; a remote
    joiner silently skipping a record would diverge from the source)."""
    env = sync_server
    joiner = fresh_engine(b"joiner-tde-identity-")
    cu = CatchUpClient(env["host"], env["port"], env["peer"])
    real_tail = cu._bridge.wal_tail

    def garbage_tail(peer, after_lsn, max_bytes):
        records, more = real_tail(peer, after_lsn, max_bytes)
        return (
            [(lsn, kind, b"\xff\xfe garbage") for lsn, kind, _ in records],
            more,
        )

    cu._bridge.wal_tail = garbage_tail
    with pytest.raises(TailRecordError):
        cu.full_replay(joiner)
    cu.close()


def test_catch_up_requires_fresh_engine(sync_server):
    env = sync_server
    busy = fresh_engine(b"joiner-bsy-identity-")
    grow_history(busy, proposals=1, voters=1)
    with CatchUpClient(env["host"], env["port"], env["peer"]) as cu:
        with pytest.raises(SyncStateError):
            cu.catch_up(busy)


def test_stale_snapshot_chunk_status(sync_server):
    env = sync_server
    client = env["client"]
    manifest = client.sync_manifest(env["peer"])
    # Move the watermark and force a rebuild: the old snapshot_id dies.
    env["source"].sweep_timeouts(NOW + 2)
    rebuilt = client.sync_manifest(env["peer"])
    assert rebuilt["snapshot_id"] != manifest["snapshot_id"]
    with pytest.raises(BridgeError) as excinfo:
        client.sync_chunk(env["peer"], manifest["snapshot_id"], 0)
    assert excinfo.value.status == P.STATUS_SYNC_STALE


def test_sync_opcodes_reject_undurable_peer():
    server = BridgeServer(capacity=16, voter_capacity=8)  # no wal_dir
    with server:
        host, port = server.address
        with BridgeClient(host, port) as client:
            peer, _ = client.add_peer()
            with pytest.raises(BridgeError) as excinfo:
                client.sync_manifest(peer)
            assert excinfo.value.status == P.STATUS_BAD_REQUEST
            with pytest.raises(BridgeError):
                client.wal_tail(peer, 0)


# ── Fleet catch_up_shard ───────────────────────────────────────────────


def _fleet_signer_factory(k: int):
    return StubConsensusSigner(bytes([k + 1]) * 20)


def test_catch_up_shard_recovers_from_peer(tmp_path):
    from hashgraph_tpu.parallel import ConsensusFleet

    fleet = ConsensusFleet(
        _fleet_signer_factory, n_shards=2,
        capacity_per_shard=32, voter_capacity=8,
        wal_root=str(tmp_path / "fleet-wal"),
    )
    server = BridgeServer(
        capacity=64, voter_capacity=8,
        wal_dir=str(tmp_path / "peer-wal"), wal_fsync="off",
        signer_factory=StubConsensusSigner,
    )
    try:
        with server:
            host, port = server.address
            with BridgeClient(host, port) as client:
                src_peer, identity = client.add_peer(os.urandom(32))
                source = server.durable_engine(identity)
                # Identical traffic to the fleet shard and the source
                # peer: the peer is the replica catch-up later syncs from.
                scope = next(
                    f"s{i}" for i in range(1000)
                    if fleet.owner_of(f"s{i}") == fleet.shard_ids[0]
                )
                scratch = fresh_engine(b"scratch-identity-xxx")
                (minted,) = scratch.create_proposals(scope, [request()], NOW)
                signers = [StubConsensusSigner(os.urandom(20)) for _ in range(3)]
                chain = minted.clone()
                for s in signers:
                    chain.votes.append(build_vote(chain, True, s, NOW + 1))
                assert fleet.deliver_proposal(scope, chain, NOW) == int(
                    StatusCode.OK
                )
                assert source.deliver_proposal(scope, chain, NOW) == int(
                    StatusCode.OK
                )
                victim = fleet.shard_ids[0]
                fleet.crash_shard(victim)
                fleet.catch_up_shard(victim, host, port, src_peer)
                shard = fleet.shard(victim)
                assert shard.available
                assert state_fingerprint(shard.engine) == state_fingerprint(
                    source
                )
                occ = fleet.occupancy()[victim]
                assert occ["catch_up"]["sessions_installed"] == 1
                assert occ["catch_up"]["votes_verified"] == 3
                health = fleet.health_report(NOW + 2)[victim]
                assert health["catch_up"]["sessions_installed"] == 1
                # The recovered shard serves immediately.
                late = build_vote(
                    fleet.get_proposal(scope, chain.proposal_id),
                    True,
                    StubConsensusSigner(os.urandom(20)),
                    NOW + 2,
                )
                statuses = fleet.ingest_votes([(scope, late)], NOW + 2)
                assert int(statuses[0]) in (
                    int(StatusCode.OK), int(StatusCode.ALREADY_REACHED)
                )
    finally:
        fleet.close()


def test_recover_shard_surfaces_wal_recover_stats(tmp_path):
    from hashgraph_tpu.parallel import ConsensusFleet

    fleet = ConsensusFleet(
        _fleet_signer_factory, n_shards=2,
        capacity_per_shard=32, voter_capacity=8,
        wal_root=str(tmp_path / "fleet-wal"),
    )
    try:
        scope = next(
            f"r{i}" for i in range(1000)
            if fleet.owner_of(f"r{i}") == fleet.shard_ids[1]
        )
        fleet.create_proposals(scope, [request()], NOW)
        victim = fleet.shard_ids[1]
        fleet.crash_shard(victim)
        # Torn tail: append garbage to the last segment so replay reports
        # repaired bytes... recovery truncates silently; instead corrupt a
        # MIDDLE segment to surface dropped_segments? A clean log still
        # surfaces the stats block with zero corruption counters — the
        # operator contract is "the numbers are in the readout".
        fleet.recover_shard(victim)
        occ = fleet.occupancy()[victim]
        assert "wal_recover" in occ
        assert occ["wal_recover"]["records_applied"] >= 1
        assert occ["wal_recover"]["torn_bytes"] == 0
        assert occ["wal_recover"]["dropped_segments"] == 0
        assert occ["wal_recover"]["decode_errors"] == 0
        health = fleet.health_report(NOW)[victim]
        assert health["wal_recover"] == occ["wal_recover"]
    finally:
        fleet.close()


class TestSyncTimeouts:
    """Wall-clock timeouts on catch-up network operations (satellite): a
    stalled source raises the typed SyncTimeoutError instead of hanging
    the joiner thread; verified progress survives in the CatchUpState."""

    def test_stalled_source_socket_times_out_typed(self):
        import socket as _socket
        import threading

        from hashgraph_tpu.sync import SyncTimeoutError

        listener = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        listener.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        host, port = listener.getsockname()[:2]
        held: list = []

        def accept_and_stall():
            conn, _ = listener.accept()
            held.append(conn)  # read the request, answer NOTHING

        thread = threading.Thread(target=accept_and_stall, daemon=True)
        thread.start()
        engine = fresh_engine(b"stalled-joiner------")
        client = CatchUpClient(host, port, 1, timeout=0.3)
        try:
            with pytest.raises(SyncTimeoutError) as excinfo:
                client.catch_up(engine)
            assert excinfo.value.operation == "manifest request"
            assert excinfo.value.timeout == 0.3
        finally:
            client.close()
            for conn in held:
                conn.close()
            listener.close()

    def test_timeout_during_chunk_names_the_operation(self):
        from hashgraph_tpu.sync import SyncTimeoutError

        class StallingBridge:
            def __init__(self):
                self.manifest_calls = 0

            def sync_manifest(self, peer, max_chunk_bytes=0):
                self.manifest_calls += 1
                return {
                    "snapshot_id": 1, "watermark": 5, "total_bytes": 64,
                    "chunk_bytes": 64, "session_count": 1,
                    "config_count": 0, "chunk_count": 1,
                    "digests": [b"\x00" * 32],
                }

            def sync_chunk(self, peer, snapshot_id, index):
                raise TimeoutError("recv timed out")

            def wal_tail(self, peer, after_lsn, max_bytes=0):
                raise AssertionError("never reached")

            def close(self):
                pass

        engine = fresh_engine(b"chunk-stall-joiner--")
        client = CatchUpClient(
            "ignored", 0, 1, timeout=0.5, bridge=StallingBridge()
        )
        with pytest.raises(SyncTimeoutError) as excinfo:
            client.catch_up(engine)
        assert "chunk 0" in excinfo.value.operation
        # Progress stays resumable: the manifest survived into the state.
        assert client.state.manifest is not None
        client.close()
