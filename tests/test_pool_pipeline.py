"""Async ingest pipelining: ordering guarantees and mutation fencing."""

import numpy as np
import pytest

from hashgraph_tpu.engine.pool import ProposalPool
from hashgraph_tpu.ops import required_votes_np
from hashgraph_tpu.errors import StatusCode

NOW = 1_700_000_000


def make_pool(p=8, v=8):
    pool = ProposalPool(p, v)
    pool.allocate_batch(
        keys=[("s", i) for i in range(p)],
        n=np.full(p, v),
        req=required_votes_np(np.full(p, v), 1.0),
        cap=np.full(p, 2),
        gossip=np.ones(p, bool),
        liveness=np.ones(p, bool),
        expiry=np.full(p, NOW + 100),
        created_at=np.full(p, NOW),
    )
    return pool


def dispatch(pool, lane):
    p = pool.capacity
    return pool.ingest_async(
        np.arange(p, dtype=np.int64),
        np.full(p, lane, np.int32),
        np.ones(p, bool),
        NOW,
    )


class TestPipelineDiscipline:
    def test_pipelined_dispatches_complete_in_order(self):
        pool = make_pool()
        pends = [dispatch(pool, lane) for lane in range(4)]
        results = pool.complete_all(pends)
        for statuses, _ in results:
            assert all(s == int(StatusCode.OK) for s in statuses)
        assert int(np.asarray(pool._tot)[0]) == 4

    def test_out_of_order_completion_rejected(self):
        pool = make_pool()
        p1 = dispatch(pool, 0)
        p2 = dispatch(pool, 1)
        with pytest.raises(RuntimeError, match="dispatch order"):
            pool.complete(p2)
        pool.complete(p1)
        pool.complete(p2)

    def test_mutations_fenced_while_inflight(self):
        pool = make_pool()
        pending = dispatch(pool, 0)
        with pytest.raises(RuntimeError, match="in flight"):
            pool.timeout([0])
        with pytest.raises(RuntimeError, match="in flight"):
            pool.release([0])
        with pytest.raises(RuntimeError, match="in flight"):
            pool.load_rows(
                [0],
                np.array([1]),
                np.array([0]),
                np.array([0]),
                np.zeros((1, pool.voter_capacity), bool),
                np.zeros((1, pool.voter_capacity), bool),
            )
        pool.complete(pending)
        pool.timeout([0])  # allowed again once drained


class TestLanelessFreshDispatch:
    """>64-lane pools ship fresh grids without the lane plane (uint8
    value/valid cells; lanes reconstructed on device as the within-slot
    arrival index). These tests drive the POOL dispatch layer — the
    gating, the lanes==col guard, and the sharded laneless kernel — not
    just the ops-level kernel."""

    def _wide_pool(self, pool_cls=ProposalPool, p=12, v=96, **kw):
        pool = pool_cls(p, v, **kw) if kw else pool_cls(p, v)
        pool.allocate_batch(
            keys=[("s", i) for i in range(pool.capacity)],
            n=np.full(pool.capacity, v),
            req=required_votes_np(np.full(pool.capacity, v), 2.0 / 3.0),
            cap=np.full(pool.capacity, v + 1),
            gossip=np.zeros(pool.capacity, bool),
            liveness=np.ones(pool.capacity, bool),
            expiry=np.full(pool.capacity, NOW + 100),
            created_at=np.full(pool.capacity, NOW),
        )
        return pool

    def _grouped_batch(self, pool, depth):
        p = pool.capacity
        uniq = np.arange(p, dtype=np.int64)
        rows = np.repeat(uniq, depth)
        cols = np.tile(np.arange(depth, dtype=np.int64), p)
        vals = (np.arange(p * depth) % 3 != 0).astype(bool)
        return uniq, rows, cols, cols.astype(np.int32), vals

    def test_laneless_matches_scan_on_wide_pool(self):
        depth = 80  # > 64 lanes used, exercising the wide-lane range
        pool_a = self._wide_pool()
        uniq, rows, cols, lanes, vals = self._grouped_batch(pool_a, depth)
        pa = pool_a.ingest_async_grouped(
            uniq, rows, cols, depth, lanes, vals, NOW, fresh=True
        )
        (st_a, tr_a), = pool_a.complete_all([pa])
        pool_b = self._wide_pool()
        pb = pool_b.ingest_async_grouped(
            uniq, rows, cols, depth, lanes, vals, NOW, fresh=False
        )
        (st_b, tr_b), = pool_b.complete_all([pb])
        assert st_a.tolist() == st_b.tolist()
        assert sorted(tr_a) == sorted(tr_b)

    def test_laneless_guard_rejects_non_arrival_lanes(self):
        pool = self._wide_pool()
        depth = 4
        uniq, rows, cols, lanes, vals = self._grouped_batch(pool, depth)
        with pytest.raises(ValueError, match="arrival index"):
            pool.ingest_async_grouped(
                uniq, rows, cols, depth, lanes[::-1].copy(), vals, NOW,
                fresh=True,
            )

    def test_sharded_laneless_matches_single_device(self):
        import jax

        from hashgraph_tpu.parallel.sharded import ShardedPool

        depth = 70
        n_dev = len(jax.devices())
        p = 2 * n_dev  # 2 slots per device, any mesh size
        single = self._wide_pool(p=p)
        uniq, rows, cols, lanes, vals = self._grouped_batch(single, depth)
        ps = single.ingest_async_grouped(
            uniq, rows, cols, depth, lanes, vals, NOW, fresh=True
        )
        (st_s, _), = single.complete_all([ps])

        sharded = self._wide_pool(
            pool_cls=lambda cap, v: ShardedPool(cap // n_dev, v), p=p
        )
        assert sharded.capacity == single.capacity
        pd = sharded.ingest_async_grouped(
            uniq, rows, cols, depth, lanes, vals, NOW, fresh=True
        )
        (st_d, _), = sharded.complete_all([pd])
        assert st_s.tolist() == st_d.tolist()
