"""Async ingest pipelining: ordering guarantees and mutation fencing."""

import numpy as np
import pytest

from hashgraph_tpu.engine.pool import ProposalPool
from hashgraph_tpu.ops import required_votes_np
from hashgraph_tpu.errors import StatusCode

NOW = 1_700_000_000


def make_pool(p=8, v=8):
    pool = ProposalPool(p, v)
    pool.allocate_batch(
        keys=[("s", i) for i in range(p)],
        n=np.full(p, v),
        req=required_votes_np(np.full(p, v), 1.0),
        cap=np.full(p, 2),
        gossip=np.ones(p, bool),
        liveness=np.ones(p, bool),
        expiry=np.full(p, NOW + 100),
        created_at=np.full(p, NOW),
    )
    return pool


def dispatch(pool, lane):
    p = pool.capacity
    return pool.ingest_async(
        np.arange(p, dtype=np.int64),
        np.full(p, lane, np.int32),
        np.ones(p, bool),
        NOW,
    )


class TestPipelineDiscipline:
    def test_pipelined_dispatches_complete_in_order(self):
        pool = make_pool()
        pends = [dispatch(pool, lane) for lane in range(4)]
        results = pool.complete_all(pends)
        for statuses, _ in results:
            assert all(s == int(StatusCode.OK) for s in statuses)
        assert int(np.asarray(pool._tot)[0]) == 4

    def test_out_of_order_completion_rejected(self):
        pool = make_pool()
        p1 = dispatch(pool, 0)
        p2 = dispatch(pool, 1)
        with pytest.raises(RuntimeError, match="dispatch order"):
            pool.complete(p2)
        pool.complete(p1)
        pool.complete(p2)

    def test_mutations_fenced_while_inflight(self):
        pool = make_pool()
        pending = dispatch(pool, 0)
        with pytest.raises(RuntimeError, match="in flight"):
            pool.timeout([0])
        with pytest.raises(RuntimeError, match="in flight"):
            pool.release([0])
        with pytest.raises(RuntimeError, match="in flight"):
            pool.load_rows(
                [0],
                np.array([1]),
                np.array([0]),
                np.array([0]),
                np.zeros((1, pool.voter_capacity), bool),
                np.zeros((1, pool.voter_capacity), bool),
            )
        pool.complete(pending)
        pool.timeout([0])  # allowed again once drained
