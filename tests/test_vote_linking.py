"""Hashgraph linking rules (reference: tests/vote_tests.rs)."""

from hashgraph_tpu import (
    ConsensusConfig,
    ConsensusService,
    BroadcastEventBus,
    CreateProposalRequest,
    EthereumConsensusSigner,
    InMemoryConsensusStorage,
    build_vote,
    validate_proposal,
)

from common import NOW

SCOPE = "vote_scope"


def make_owner_service():
    owner = EthereumConsensusSigner.random()
    service = ConsensusService(InMemoryConsensusStorage(), BroadcastEventBus(), owner)
    request = CreateProposalRequest(
        name="Vote Test Proposal",
        payload=b"",
        proposal_owner=owner.identity(),
        expected_voters_count=3,
        expiration_timestamp=120,
        liveness_criteria_yes=True,
    )
    proposal = service.create_proposal_with_config(
        SCOPE, request, ConsensusConfig.gossipsub(), NOW
    )
    proposal = service.cast_vote_and_get_proposal(SCOPE, proposal.proposal_id, True, NOW)
    return service, owner, proposal


def test_received_hash_for_new_voter():
    """reference: tests/vote_tests.rs:26-68 — a new voter has empty parent and
    received = latest vote's hash."""
    _, _, proposal = make_owner_service()
    other_voter = EthereumConsensusSigner.random()
    vote = build_vote(proposal, True, other_voter, NOW)

    assert vote.parent_hash == b""
    assert vote.received_hash == proposal.votes[0].vote_hash

    with_vote = proposal.clone()
    with_vote.votes.append(vote)
    validate_proposal(with_vote, EthereumConsensusSigner, NOW)


def test_parent_hash_for_same_voter():
    """reference: tests/vote_tests.rs:71-114 — the same voter's second vote
    chains parent to their prior vote."""
    _, owner, proposal = make_owner_service()
    second_vote = build_vote(proposal, False, owner, NOW)

    assert second_vote.received_hash == proposal.votes[0].vote_hash
    assert second_vote.parent_hash == proposal.votes[0].vote_hash

    with_vote = proposal.clone()
    with_vote.votes.append(second_vote)
    validate_proposal(with_vote, EthereumConsensusSigner, NOW)
