"""Apply reactor: cross-connection continuous batching on the wire path.

Covers the ISSUE 19 acceptance surface: merge correctness against the
single-dispatch oracle, window scheduling rules (equal-``now`` merges,
rows/bytes closes, manual-mode determinism, adaptive delay), error
scatter, reactor-on vs reactor-off DECISION IDENTITY on a randomized
multi-connection workload (byte-identical fingerprints AND per-row
statuses), the serial-lane admission shed counting queued reactor rows,
and the full chaos corpus with the reactor forced on."""

import random
import threading

import numpy as np
import pytest

from hashgraph_tpu import build_vote
from hashgraph_tpu.bridge import columnar as WC
from hashgraph_tpu.bridge import protocol as P
from hashgraph_tpu.bridge.reactor import (
    ApplyReactor,
    merge_entries,
    reactor_enabled,
)
from hashgraph_tpu.bridge.server import BridgeServer
from hashgraph_tpu.signing.stub import StubConsensusSigner
from hashgraph_tpu.sync.snapshot import state_fingerprint
from hashgraph_tpu.wire import Proposal, Vote

NOW = 1_700_000_000


def _columnar(votes: "list[bytes]"):
    """(cols, data, offsets) for a list of canonical wire-vote blobs."""
    offsets = np.zeros(len(votes) + 1, np.int64)
    np.cumsum([len(v) for v in votes], out=offsets[1:])
    data = np.frombuffer(b"".join(votes), np.uint8)
    cols, flags = WC.parse_vote_columns(data, offsets)
    assert flags.all()
    return cols, data, offsets


def _proposal(pid: int, voters: int = 64, tag: str = "p") -> Proposal:
    return Proposal(
        name=f"{tag}-{pid}",
        payload=b"x",
        proposal_id=pid,
        proposal_owner=b"\x11" * 20,
        expected_voters_count=voters,
        timestamp=NOW,
        expiration_timestamp=NOW + 3_600,
        liveness_criteria_yes=True,
    )


def _chain(proposal: Proposal, n: int, salt: int = 0) -> "list[bytes]":
    out = []
    for i in range(n):
        signer = StubConsensusSigner(bytes([salt + i + 1]) * 20)
        vote = build_vote(proposal, True, signer, NOW + 1)
        proposal.votes.append(vote)
        out.append(vote.encode())
    return out


class _RecordingEngine:
    """Columnar-capable fake: records each fused dispatch and returns
    row-index codes so scatter slices are checkable."""

    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def ingest_wire_columnar(
        self, scopes, scope_idx, cols, data, offsets, now,
        max_depth=8, stage_seconds=None, _prepass=None, _buf=None,
    ):
        if self.fail:
            raise RuntimeError("engine exploded")
        self.calls.append(
            (list(scopes), np.asarray(scope_idx).copy(),
             np.asarray(cols).copy(), np.asarray(data).copy(),
             np.asarray(offsets).copy(), now)
        )
        if stage_seconds is not None:
            stage_seconds["apply"] = stage_seconds.get("apply", 0.0) + 0.001
        return np.arange(len(cols), dtype=np.int64)


class TestMergeEntries:
    def test_merged_frame_is_bytewise_consistent(self):
        """Two frames merged: offsets contiguous over the concatenated
        data, every shifted byte-offset column still points at the same
        bytes (owner + signature spot-checked per row)."""
        p1, p2 = _proposal(1), _proposal(2)
        votes_a = _chain(p1, 3, salt=0)
        votes_b = _chain(p2, 2, salt=10)
        reactor = ApplyReactor()
        engine = _RecordingEngine()
        reactor.submit(engine, ["a"], np.zeros(3, np.int64),
                       *_columnar(votes_a), NOW + 1)
        reactor.submit(engine, ["b"], np.zeros(2, np.int64),
                       *_columnar(votes_b), NOW + 1)
        reactor.flush()
        assert len(engine.calls) == 1  # ONE fused dispatch
        scopes, sidx, cols, data, offsets, now = engine.calls[0]
        assert scopes == ["a", "b"]
        assert sidx.tolist() == [0, 0, 0, 1, 1]
        assert now == NOW + 1
        blobs = votes_a + votes_b
        assert offsets[0] == 0 and offsets[-1] == len(data)
        buf = data.tobytes()
        for i, blob in enumerate(blobs):
            assert buf[int(offsets[i]):int(offsets[i + 1])] == blob
            vote = Vote.decode(blob)
            o, ol = int(cols[i][WC.COL_OWNER_OFF]), int(cols[i][WC.COL_OWNER_LEN])
            assert buf[o:o + ol] == vote.vote_owner
            s, sl = int(cols[i][WC.COL_SIG_OFF]), int(cols[i][WC.COL_SIG_LEN])
            assert buf[s:s + sl] == vote.signature

    def test_scatter_slices_codes_back_per_entry(self):
        reactor = ApplyReactor()
        engine = _RecordingEngine()
        p1, p2 = _proposal(1), _proposal(2)
        h1 = reactor.submit(engine, ["a"], np.zeros(3, np.int64),
                            *_columnar(_chain(p1, 3)), NOW + 1)
        h2 = reactor.submit(engine, ["b"], np.zeros(2, np.int64),
                            *_columnar(_chain(p2, 2, salt=10)), NOW + 1)
        reactor.flush()
        assert h1.wait(1).tolist() == [0, 1, 2]
        assert h2.wait(1).tolist() == [3, 4]  # rows 3-4 of the fusion

    def test_merged_prepass_chains_sources_and_joins_bufs(self):
        from hashgraph_tpu.engine.engine import WireVotePrepass

        p1, p2 = _proposal(1), _proposal(2)

        class _E:
            def __init__(self, blobs):
                self.blobs = blobs

        entries = []
        row_base = 0
        for blobs in (_chain(p1, 2), _chain(p2, 3, salt=10)):
            cols, data, offsets = _columnar(blobs)
            pre = np.zeros(len(blobs), np.int32)
            pre[0] = 7  # a pre-rejected row per entry
            crypto = np.nonzero(pre == 0)[0].astype(np.int64)
            verdicts = [True] * len(crypto)
            prepass = WireVotePrepass(
                pre, crypto, lambda v=verdicts: v, buf=data.tobytes()
            )
            from hashgraph_tpu.bridge.reactor import _Entry, ReactorHandle

            entries.append(_Entry(
                ["s"], np.zeros(len(blobs), np.int64), cols, data, offsets,
                prepass, ReactorHandle(len(blobs)),
            ))
            row_base += len(blobs)
        scopes, sidx, cols, data, offsets, merged = merge_entries(entries)
        assert merged.pre_status.tolist() == [7, 0, 7, 0, 0]
        assert merged.crypto_rows.tolist() == [1, 3, 4]  # shifted by row base
        assert merged.buf == data.tobytes()
        assert len(merged.collect()) == 3


class TestWindowRules:
    def test_manual_mode_dispatches_nothing_until_flush(self):
        reactor = ApplyReactor()
        engine = _RecordingEngine()
        p = _proposal(1)
        handle = reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                                *_columnar(_chain(p, 2)), NOW + 1)
        assert not handle.done and not engine.calls
        assert reactor.pending(engine) == (1, 2)
        reactor.flush(engine)
        assert handle.done and len(engine.calls) == 1
        assert reactor.pending(engine) == (0, 0)

    def test_now_change_closes_the_open_window(self):
        reactor = ApplyReactor()
        engine = _RecordingEngine()
        p1, p2 = _proposal(1), _proposal(2)
        reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                       *_columnar(_chain(p1, 2)), NOW + 1)
        reactor.submit(engine, ["b"], np.zeros(2, np.int64),
                       *_columnar(_chain(p2, 2, salt=10)), NOW + 2)
        reactor.flush()
        # Different logical now NEVER merges: two dispatches, each at
        # its own now — the unconditional determinism guarantee.
        assert [call[5] for call in engine.calls] == [NOW + 1, NOW + 2]

    def test_engines_get_separate_windows(self):
        reactor = ApplyReactor()
        e1, e2 = _RecordingEngine(), _RecordingEngine()
        p1, p2 = _proposal(1), _proposal(2)
        reactor.submit(e1, ["a"], np.zeros(2, np.int64),
                       *_columnar(_chain(p1, 2)), NOW + 1)
        reactor.submit(e2, ["b"], np.zeros(2, np.int64),
                       *_columnar(_chain(p2, 2, salt=10)), NOW + 1)
        reactor.flush()
        assert len(e1.calls) == 1 and len(e2.calls) == 1

    def test_max_rows_closes_and_preserves_order(self):
        reactor = ApplyReactor(max_rows=4)
        engine = _RecordingEngine()
        handles = []
        for i in range(3):
            p = _proposal(i + 1)
            handles.append(reactor.submit(
                engine, [f"s{i}"], np.zeros(2, np.int64),
                *_columnar(_chain(p, 2, salt=10 * i)), NOW + 1,
            ))
        reactor.flush()
        # 2+2 rows hit max_rows=4 -> window 1; the third frame opens
        # window 2. Creation order is dispatch order.
        assert [len(call[0]) for call in engine.calls] == [2, 1]
        for handle in handles:
            assert handle.wait(1) is not None

    def test_adaptive_delay_shrinks_and_grows(self):
        reactor = ApplyReactor(max_rows=2, max_delay=0.001, min_delay=0.0001)
        engine = _RecordingEngine()
        p = _proposal(1)
        start = reactor._delay
        reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                       *_columnar(_chain(p, 2)), NOW + 1)  # rows close
        grown = reactor._delay
        assert grown == start  # already at max_delay, growth capped
        # Single-entry deadline close halves the delay.
        p2 = _proposal(2)
        reactor.submit(engine, ["b"], np.zeros(1, np.int64),
                       *_columnar(_chain(p2, 1, salt=10)), NOW + 1)
        reactor._close(reactor._queues[id(engine)], "deadline")
        assert reactor._delay < grown
        reactor.flush()

    def test_dispatch_error_reaches_every_handle(self):
        reactor = ApplyReactor()
        engine = _RecordingEngine(fail=True)
        p1, p2 = _proposal(1), _proposal(2)
        h1 = reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                            *_columnar(_chain(p1, 2)), NOW + 1)
        h2 = reactor.submit(engine, ["b"], np.zeros(2, np.int64),
                            *_columnar(_chain(p2, 2, salt=10)), NOW + 1)
        reactor.flush()
        for handle in (h1, h2):
            assert handle.done and handle.error is not None
            with pytest.raises(RuntimeError, match="engine exploded"):
                handle.wait(1)

    def test_started_mode_deadline_flushes_without_explicit_flush(self):
        reactor = ApplyReactor(max_delay=0.005, min_delay=0.005,
                               adaptive=False)
        engine = _RecordingEngine()
        reactor.start()
        try:
            p = _proposal(1)
            handle = reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                                    *_columnar(_chain(p, 2)), NOW + 1)
            assert handle.wait(5.0).tolist() == [0, 1]
        finally:
            reactor.stop()

    def test_stop_drains_queued_windows(self):
        reactor = ApplyReactor(max_delay=60.0, min_delay=60.0,
                               adaptive=False)
        engine = _RecordingEngine()
        reactor.start()
        p = _proposal(1)
        handle = reactor.submit(engine, ["a"], np.zeros(2, np.int64),
                                *_columnar(_chain(p, 2)), NOW + 1)
        reactor.stop()  # never hit the 60s deadline: stop must drain
        assert handle.done and handle.wait(0).tolist() == [0, 1]

    def test_env_override_contract(self, monkeypatch):
        monkeypatch.delenv("HASHGRAPH_TPU_APPLY_REACTOR", raising=False)
        assert reactor_enabled(None) is False  # default OFF
        assert reactor_enabled(True) is True
        monkeypatch.setenv("HASHGRAPH_TPU_APPLY_REACTOR", "1")
        assert reactor_enabled(None) is True
        assert reactor_enabled(False) is False  # explicit wins


# ── decision identity: reactor on == reactor off, exactly ──────────────


def _build_plans(n_conns: int, seed: int):
    """Per-connection replayable workload plans: ``(scope, proposal
    blob, vote-blob chunks)`` built ONCE so both arms of an A/B see
    byte-identical wire traffic (``build_vote`` mints uuid4 vote ids —
    regenerating per arm would diverge the *inputs*, not the arms)."""
    rng = random.Random(seed)
    plans = []
    for c in range(n_conns):
        plan = []
        for p in range(rng.randint(1, 3)):
            scope = f"c{c}-s{p}"
            voters = rng.randint(6, 18)
            proposal = _proposal(1 + c * 10 + p, voters=voters + 10,
                                 tag=scope)
            blob = proposal.encode()
            votes = []
            for i in range(voters):
                signer = StubConsensusSigner(
                    bytes([c * 40 + i + 1]) * 20
                )
                vote = build_vote(proposal, True, signer, NOW + 1)
                proposal.votes.append(vote)
                votes.append(vote.encode())
            size = rng.choice((2, 3, 5))
            chunks = [votes[i:i + size] for i in range(0, len(votes), size)]
            plan.append((scope, blob, chunks))
        plans.append(plan)
    return plans


def _run_workload(server: BridgeServer, plans):
    """Replay pre-built plans: one REAL TCP connection per plan, each
    owning disjoint scopes, firing interleaved chunked vote batches
    from its own thread. Returns (statuses by (conn, frame), state
    fingerprint)."""
    from hashgraph_tpu.bridge.client import BridgeClient

    host, port = server.address
    setup = BridgeClient(host, port, timeout=30.0)
    pid, _identity = setup.add_peer(b"\x11" * 32)
    for plan in plans:
        for scope, blob, _chunks in plan:
            setup.process_proposal(pid, scope, blob, NOW)
    results: dict = {}
    errors: list = []

    def run_conn(c: int) -> None:
        try:
            client = BridgeClient(host, port, timeout=30.0)
            try:
                frames = []
                for scope, _blob, chunks in plans[c]:
                    for part in chunks:
                        status_list = client.process_votes(
                            pid, scope, part, NOW + 1
                        )
                        frames.append((scope, tuple(status_list)))
                results[c] = frames
            finally:
                client.close()
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append((c, exc))

    threads = [
        threading.Thread(target=run_conn, args=(c,))
        for c in range(len(plans))
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(60)
    assert not errors, errors
    fingerprint = setup.state_fingerprint(pid)
    setup.close()
    return results, fingerprint


@pytest.mark.parametrize("seed", [11, 23])
def test_decision_identity_reactor_on_vs_off(seed):
    """The tentpole's safety bar: a randomized multi-connection workload
    produces BYTE-IDENTICAL per-row statuses and state fingerprints with
    the reactor on and off. Per-connection scopes are disjoint (rows
    within one window from different connections are order-free, same
    as today's concurrent dispatches), so statuses are deterministic."""
    plans = _build_plans(n_conns=3, seed=seed)
    outcomes = {}
    for pin in (False, True):
        server = BridgeServer(
            capacity=64, voter_capacity=40,
            signer_factory=StubConsensusSigner,
            wire_columnar=True,
            apply_reactor=(
                ApplyReactor(max_delay=0.002, min_delay=0.0005)
                if pin else False
            ),
        )
        server.start()
        try:
            outcomes[pin] = _run_workload(server, plans)
        finally:
            server.stop()
    (status_off, fp_off), (status_on, fp_on) = outcomes[False], outcomes[True]
    assert status_on == status_off
    assert fp_on == fp_off


def test_sync_dispatch_parity_with_mixed_bad_rows():
    """Embedded (manual-reactor) parity including per-row errors: a
    flipped signature and a duplicate must land the same codes in the
    same rows either way. The frame bytes are built ONCE (vote ids are
    uuid4-minted) and replayed into both arms."""
    proposal = _proposal(5, voters=16)
    blob = proposal.encode()
    rows = _chain(proposal, 6)
    flipped = bytearray(rows[3])
    flipped[-1] ^= 0xFF
    batch = rows[:3] + [bytes(flipped), rows[0], rows[4]]
    responses = {}
    fingerprints = {}
    for pin in (False, True):
        server = BridgeServer(
            capacity=16, voter_capacity=12,
            signer_factory=StubConsensusSigner, wire_columnar=True,
            apply_reactor=pin,
        )
        server.start_embedded()
        try:
            st, out = server.dispatch_frame(P.OP_ADD_PEER, P.u8(32) + b"\x11" * 32)
            assert st == P.STATUS_OK
            pid = P.Cursor(out).u32()
            st, _ = server.dispatch_frame(
                P.OP_PROCESS_PROPOSAL,
                P.u32(pid) + P.string("m") + P.u64(NOW) + P.blob(blob),
            )
            assert st == P.STATUS_OK
            responses[pin] = server.dispatch_frame(
                P.OP_VOTE_BATCH,
                P.encode_vote_batch(NOW + 1, [(pid, "m", batch)]),
            )
            fingerprints[pin] = state_fingerprint(server.peer_engine(pid))
        finally:
            server.stop()
    assert responses[True] == responses[False]
    assert fingerprints[True] == fingerprints[False]


# ── satellite: admission shed counts queued reactor rows ───────────────


def test_shed_counts_queued_reactor_rows():
    """A parked (huge-threshold, never-flushing) window's frames must
    still count toward the serial-lane admission limit: the shed sees
    reactor_frames/reactor_rows, so a full window cannot silently
    bypass overload control."""
    server = BridgeServer(
        capacity=8, voter_capacity=8, ordered_admission_limit=2,
        apply_reactor=ApplyReactor(
            max_rows=10**9, max_bytes=10**9, max_delay=10.0,
            min_delay=10.0, adaptive=False,
        ),
    )

    class _FakeConn:
        def __init__(self):
            self.sent = b""

        def sendall(self, data: bytes) -> None:
            self.sent += data

    from hashgraph_tpu.bridge.server import _ConnState

    state = _ConnState.__new__(_ConnState)
    state.write_lock = threading.Lock()
    state.reactor_lock = threading.Lock()
    state.reactor_frames = 0
    state.reactor_rows = 0

    class _Lane:
        def depth(self) -> int:
            return 0  # the lane itself is EMPTY: work sits in windows

    state.ordered = _Lane()
    mutating = next(iter(P.MUTATING_OPCODES))
    conn = _FakeConn()
    # No queued reactor work: admitted.
    assert not server._shed_retry_after(conn, state, mutating, 1)
    # Two frames' rows parked in an unflushed window: at the limit.
    state.reactor_frames = 2
    state.reactor_rows = 4096
    assert server._shed_retry_after(conn, state, mutating, 2)
    status, corr, cursor = P.parse_frame(conn.sent[4:], tagged=True)
    assert status == P.STATUS_RETRY_AFTER and corr == 2
    hint = float(cursor.string())
    # Queued rows scale the hint beyond the frame count alone.
    assert hint > 2 / 1000.0
    server.stop()


# ── satellite: chaos corpus with the reactor forced on ─────────────────


class TestChaosCorpusReactorOn:
    @pytest.mark.slow
    @pytest.mark.parametrize("name", sorted(
        __import__(
            "hashgraph_tpu.sim.scenarios", fromlist=["SCENARIOS"]
        ).SCENARIOS
    ))
    def test_scenario_passes_with_reactor_forced_on(self, name, tmp_path):
        from hashgraph_tpu.sim.scenarios import run_scenario

        result = run_scenario(
            name, 5, root=str(tmp_path), overrides={"apply_reactor": True}
        )
        assert result["passed"], (name, result["verdicts"], result["checks"])

    def test_columnar_wire_storm_reactor_on_matches_reactor_off(self):
        """The decision-identity bar inside the simulator: the
        columnar-wire-storm scenario's verdict fingerprints must be
        IDENTICAL with the reactor on and off (flush-on-tick manual
        mode keeps the sim seed-deterministic)."""
        from hashgraph_tpu.sim.scenarios import run_scenario

        on = run_scenario(
            "columnar-wire-storm", 5, overrides={"apply_reactor": True}
        )
        off = run_scenario("columnar-wire-storm", 5)
        assert on["passed"] and off["passed"]
        assert (
            on["verdicts"]["convergence"]["fingerprints"]
            == off["verdicts"]["convergence"]["fingerprints"]
        )
