"""Crash-recovery property test (seeded randomized trials, no external
fuzzing dependency so it always runs in CI).

Property: for a randomized workload logged to a WAL and truncated at an
ARBITRARY byte offset (torn write), ``recovery.replay`` into a fresh engine
yields an engine observably identical to a live engine that saw exactly the
surviving prefix of calls — consensus results, scope stats, vote
chains/tallies, rounds, AND continued behavior (re-ingesting any recorded
vote produces identical statuses, duplicate rejection included). A second
suite runs the same property through a snapshot + compaction cycle.

The mirror ("live engine that saw the surviving prefix") is reconstructed
from the recorded op list: the wrapper appends exactly one WAL record per
acknowledged mutator call, so record k of the log IS call k of the prefix.
"""

import os
import random

import numpy as np

from hashgraph_tpu import (
    ConsensusError,
    ConsensusFailed,
    CreateProposalRequest,
    InMemoryConsensusStorage,
    NetworkType,
    ScopeConfig,
    SessionNotFound,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.sync import state_fingerprint
from hashgraph_tpu.wal import CRASH_POINTS, DurableEngine, SimulatedCrash, replay, scan
from hashgraph_tpu.wal.segment import list_segments

from common import NOW

SCOPES = ["s0", "s1", "s2"]


def _request(rng):
    return CreateProposalRequest(
        name=f"p{rng.randrange(1 << 30)}",
        payload=os.urandom(rng.randrange(0, 12)),
        proposal_owner=b"owner",
        expected_voters_count=rng.randint(2, 5),
        expiration_timestamp=rng.randint(5, 60),
        liveness_criteria_yes=rng.random() < 0.5,
    )


def _fresh_engine(identity: bytes) -> TpuConsensusEngine:
    return TpuConsensusEngine(
        StubConsensusSigner(identity), capacity=32, voter_capacity=8
    )


def _run_workload(durable, rng, n_ops, t0=NOW):
    """Drive a random mix of mutators; returns (ops, pids) where ops[k]
    mirrors WAL record lsn t0_lsn+k one-to-one (a call that raised before
    logging appends no op, matching the wrapper's no-record behavior)."""
    ops = []
    pids = []
    remote_signers = {}
    t = t0
    for _ in range(n_ops):
        r = rng.random()
        if r < 0.30 or not pids:
            scope = rng.choice(SCOPES)
            proposal = durable.create_proposal(scope, _request(rng), t)
            ops.append(("proposal", scope, proposal.clone(), t))
            pids.append((scope, proposal.proposal_id))
            remote_signers[(scope, proposal.proposal_id)] = []
        elif r < 0.70:
            scope, pid = rng.choice(pids)
            try:
                proposal = durable.get_proposal(scope, pid)
            except SessionNotFound:
                continue  # evicted by the per-scope cap; reads log nothing
            used = remote_signers[(scope, pid)]
            if used and rng.random() < 0.3:
                signer = rng.choice(used)  # deliberate duplicate voter
            else:
                signer = StubConsensusSigner(os.urandom(20))
                used.append(signer)
            vote = build_vote(proposal, rng.random() < 0.5, signer, t)
            ops.append(("votes", scope, vote.clone(), t, False))
            try:
                durable.process_incoming_vote(scope, vote, t)
            except ConsensusError:
                pass  # rejection was logged before apply; replay re-rejects
        elif r < 0.85:
            scope, pid = rng.choice(pids)
            try:
                vote = durable.cast_vote(scope, pid, rng.random() < 0.5, t)
            except ConsensusError:
                continue  # raised before logging -> no record, no op
            ops.append(("votes", scope, vote.clone(), t, True))
        elif r < 0.92:
            scope, pid = rng.choice(pids)
            ops.append(("timeout", scope, pid, t))
            try:
                durable.handle_consensus_timeout(scope, pid, t)
            except ConsensusError:
                pass
        else:
            ops.append(("sweep", t))
            durable.sweep_timeouts(t)
        t += rng.randint(0, 3)
    return ops, pids


def _apply_op(engine, op):
    kind = op[0]
    if kind == "proposal":
        _, scope, proposal, now = op
        engine.ingest_proposals([(scope, proposal.clone())], now)
    elif kind == "votes":
        _, scope, vote, now, pre_validated = op
        engine.ingest_votes([(scope, vote.clone())], now, pre_validated=pre_validated)
    elif kind == "timeout":
        _, scope, pid, now = op
        try:
            engine.handle_consensus_timeout(scope, pid, now)
        except ConsensusError:
            pass
    elif kind == "sweep":
        engine.sweep_timeouts(op[1])
    elif kind == "config":
        _, scope, config = op
        engine.set_scope_config(scope, config)
    elif kind == "mark":
        pass
    else:  # pragma: no cover
        raise AssertionError(f"unknown mirror op {kind}")


def _observable(engine, pids):
    """Everything the acceptance criteria call observable: per-scope stats,
    consensus results, vote chains/tallies, rounds."""
    out = {}
    for scope in SCOPES:
        stats = engine.get_scope_stats(scope)
        out[("stats", scope)] = (
            stats.total_sessions,
            stats.active_sessions,
            stats.failed_sessions,
            stats.consensus_reached,
        )
    for scope, pid in pids:
        try:
            result = engine.get_consensus_result(scope, pid)
        except ConsensusFailed:
            result = "failed"
        except SessionNotFound:
            out[("session", scope, pid)] = "missing"
            continue
        session = engine.export_session(scope, pid)
        out[("session", scope, pid)] = (
            result,
            session.proposal.round,
            len(session.proposal.votes),
            tuple(sorted((o.hex(), v.vote) for o, v in session.votes.items())),
            tuple(sorted((o.hex(), val) for o, val in session.tallies.items())),
        )
    return out


def _copy_truncated(src: str, dst: str, cut: int) -> None:
    """Byte-prefix copy of a WAL directory: keep the first ``cut`` bytes of
    the concatenated segment stream (segment order = LSN order)."""
    os.makedirs(dst, exist_ok=True)
    consumed = 0
    for _base, path in list_segments(src):
        size = os.path.getsize(path)
        if cut <= consumed:
            break
        keep = min(size, cut - consumed)
        with open(path, "rb") as fh:
            data = fh.read(keep)
        with open(os.path.join(dst, os.path.basename(path)), "wb") as fh:
            fh.write(data)
        consumed += size


class TestTornTailRecoveryProperty:
    def test_randomized_torn_tail_equivalence(self, tmp_path):
        for seed in range(6):
            self._trial(seed, tmp_path / f"trial{seed}")

    def _trial(self, seed, root):
        rng = random.Random(0xC0FFEE + seed)
        identity = os.urandom(20)
        live = DurableEngine(
            _fresh_engine(identity),
            root / "wal",
            fsync_policy="off",
            segment_bytes=1024,  # small segments: cuts cross boundaries
        )
        # A scope config record up front so replay covers that kind too.
        config = ScopeConfig(network_type=NetworkType.P2P)
        live.set_scope_config("s1", config)
        ops = [("config", "s1", config)]
        more_ops, pids = _run_workload(live, rng, n_ops=30)
        ops.extend(more_ops)
        live.close()

        src = str(root / "wal")
        total = sum(os.path.getsize(p) for _, p in list_segments(src))
        assert len(scan(src).records) == len(ops)  # 1 record per call

        cut = rng.randrange(0, total + 1)
        dst = str(root / "cut")
        _copy_truncated(src, dst, cut)

        surviving = scan(dst)
        k = len(surviving.records)
        assert k <= len(ops)
        # LSNs are the contiguous prefix 1..k — truncation is whole-record.
        assert [lsn for lsn, _, _ in surviving.records] == list(range(1, k + 1))

        recovered = _fresh_engine(identity)
        stats = replay(dst, recovered)
        assert stats.errors == []
        assert stats.records_applied == k

        mirror = _fresh_engine(identity)
        for op in ops[:k]:
            _apply_op(mirror, op)

        assert _observable(recovered, pids) == _observable(mirror, pids), (
            f"seed={seed} cut={cut}/{total} k={k}"
        )

        # Continued behavior: every recorded vote (seen or unseen by the
        # prefix) gets the IDENTICAL status from both engines — duplicate
        # rejection, unknown sessions, late votes, all of it.
        vote_items = [
            (op[1], op[2].clone()) for op in ops if op[0] == "votes"
        ]
        if vote_items:
            t_end = NOW + 1000
            got_a = recovered.ingest_votes(
                [(s, v.clone()) for s, v in vote_items], t_end
            )
            got_b = mirror.ingest_votes(
                [(s, v.clone()) for s, v in vote_items], t_end
            )
            assert np.array_equal(got_a, got_b), f"seed={seed}"


class TestSnapshotCompactionRecoveryProperty:
    def test_torn_tail_after_checkpoint(self, tmp_path):
        for seed in range(3):
            self._trial(seed, tmp_path / f"trial{seed}")

    def _trial(self, seed, root):
        rng = random.Random(0xBEEF + seed)
        identity = os.urandom(20)
        live = DurableEngine(
            _fresh_engine(identity),
            root / "wal",
            fsync_policy="off",
            segment_bytes=512,
        )
        ops, pids = _run_workload(live, rng, n_ops=20)

        # Snapshot + compaction: every covered segment is deleted.
        src = str(root / "wal")
        assert len(list_segments(src)) > 1
        storage = InMemoryConsensusStorage()
        live.checkpoint(storage)
        ops.append(("mark", None))
        survivors = list_segments(src)
        assert len(survivors) == 1  # only the fresh active segment remains
        assert scan(src).watermark == len(ops) - 1  # everything pre-mark

        more_ops, more_pids = _run_workload(live, rng, n_ops=15, t0=NOW + 100)
        ops.extend(more_ops)
        pids = pids + [p for p in more_pids if p not in pids]
        live.close()

        total = sum(os.path.getsize(p) for _, p in list_segments(src))
        cut = rng.randrange(0, total + 1)
        dst = str(root / "cut")
        _copy_truncated(src, dst, cut)

        # Recover through the real entry point: snapshot, then WAL tail.
        recovered = DurableEngine(
            _fresh_engine(identity), dst, fsync_policy="off"
        )
        recovered.recover(storage)

        surviving = scan(dst)
        watermark = surviving.watermark
        mirror = _fresh_engine(identity)
        mirror.load_from_storage(storage)
        for lsn, _, _ in surviving.records:
            if lsn > watermark:
                _apply_op(mirror, ops[lsn - 1])

        assert _observable(recovered.engine, pids) == _observable(mirror, pids), (
            f"seed={seed} cut={cut}/{total}"
        )

        # The recovered node can checkpoint again and compaction still
        # holds the invariant: one active segment, nothing else.
        storage2 = InMemoryConsensusStorage()
        recovered.checkpoint(storage2)
        assert len(list_segments(dst)) == 1
        recovered.close()


class TestTwoPhaseCompaction:
    """DurableEngine.compact(): the one-safe-call second phase of the
    buffering-backend checkpoint flow — checkpoint(compact=False), make
    the snapshot durable, compact()."""

    def test_compact_requires_a_checkpoint(self, tmp_path):
        import pytest

        durable = DurableEngine(
            _fresh_engine(b"cmp"), str(tmp_path), fsync_policy="off"
        )
        durable.create_proposal("s0", _request(random.Random(1)), NOW)
        with pytest.raises(ValueError, match="no checkpoint"):
            durable.compact()
        durable.close()

    def test_compact_drops_exactly_the_covered_segments(self, tmp_path):
        rng = random.Random(7)
        durable = DurableEngine(
            _fresh_engine(b"cmp"), str(tmp_path), fsync_policy="off"
        )
        _run_workload(durable, rng, 20)
        storage = InMemoryConsensusStorage()
        durable.checkpoint(storage, compact=False)
        # Phase one rotated: the covered history is sealed but intact.
        assert len(list_segments(str(tmp_path))) == 2
        removed = durable.compact()
        assert removed == 1
        assert len(list_segments(str(tmp_path))) == 1
        # Idempotent: a second compact has nothing left to drop.
        assert durable.compact() == 0
        durable.close()

    def test_crash_between_phases_replays_to_parity(self, tmp_path):
        """Crash in the window between checkpoint(compact=False) and
        compact(): the un-compacted covered records coexist with the
        durable snapshot, and recovery (snapshot + tail, over-replaying
        the covered records the snapshot also holds) must converge to
        the same observable state as a node that never crashed."""
        rng = random.Random(11)
        identity = b"two-phase-crash-node"
        durable = DurableEngine(
            _fresh_engine(identity), str(tmp_path / "a"), fsync_policy="off"
        )
        ops, pids = _run_workload(durable, rng, 24)
        storage = InMemoryConsensusStorage()
        durable.checkpoint(storage, compact=False)
        watermark = durable.last_checkpoint_watermark
        # More traffic lands after phase one, before the "crash".
        more_ops, more_pids = _run_workload(durable, rng, 8, t0=NOW + 100)
        pids += [p for p in more_pids if p not in pids]
        durable.close()  # crash before compact()

        # Recover from the durable snapshot + the UNCOMPACTED log. The
        # embedder persisted the watermark alongside the snapshot (the
        # documented multi-snapshot discipline), so replay skips exactly
        # the covered records; passing a smaller after_lsn (over-replay)
        # must converge identically — both paths are exercised.
        for after_lsn in (watermark, max(0, watermark - 3)):
            recovered = DurableEngine(
                _fresh_engine(identity), str(tmp_path / "a"),
                fsync_policy="off",
            )
            stats = recovered.recover(storage, after_lsn=after_lsn)
            assert not stats.errors
            mirror = _fresh_engine(identity)
            for op in ops + more_ops:
                _apply_op(mirror, op)
            assert _observable(recovered.engine, pids) == _observable(
                mirror, pids
            )
            recovered.close()


class TestCrashPointMatrix:
    """Simulated ``kill -9`` at EVERY WAL crash point (the sim's crash
    hooks: append before/after, fsync before/after, segment-roll
    before/after, torn partial writes included): recovery through a
    fresh writer + ``recover()`` must land on a state whose fingerprint
    is a PREFIX of the pre-crash engine's op history — never garbage,
    never a state the live engine was not in at some op boundary."""

    def _crash_trial(self, root, point, occurrence, torn_bytes, seed=0xD1E):
        rng = random.Random(seed + occurrence)
        identity = b"crash-matrix-node\x00\x00\x00"
        fired = [0]

        def hook(p: str) -> None:
            if p == point:
                fired[0] += 1
                if fired[0] == occurrence:
                    raise SimulatedCrash(p, torn_bytes=torn_bytes)

        live = DurableEngine(
            _fresh_engine(identity),
            root,
            fsync_policy="always",   # every append crosses the fsync points
            segment_bytes=600,       # small segments: rotations fire too
            crash_hook=hook,
        )
        # Fingerprint after every completed op = the legal landing set.
        candidates = [state_fingerprint(live.engine)]
        crashed = False
        try:
            for _ in range(40):
                _run_workload(live, rng, n_ops=1)
                candidates.append(state_fingerprint(live.engine))
        except SimulatedCrash:
            crashed = True
            # A mutator can crash between engine-apply and WAL-append
            # (the documented window for locally-minted data): the
            # half-op state is also a legal recovery target when the
            # record DID reach the disk before the crash point fired.
            candidates.append(state_fingerprint(live.engine))
        if not crashed:
            live.close()
            return None  # the workload never reached this point; skip

        recovered = DurableEngine(
            _fresh_engine(identity), root, fsync_policy="off"
        )
        stats = recovered.recover()
        assert stats.errors == [], f"{point}@{occurrence}: decode faults"
        fingerprint = state_fingerprint(recovered.engine)
        assert fingerprint in candidates, (
            f"crash at {point}@{occurrence} torn={torn_bytes}: recovered "
            f"state is not an op-boundary prefix of the pre-crash engine"
        )
        recovered.close()
        return fingerprint

    def test_every_crash_point_recovers_to_a_prefix(self, tmp_path):
        ran = 0
        for point in CRASH_POINTS:
            for occurrence in (1, 3):
                for torn in (0, 9) if point == "append" else (0,):
                    root = tmp_path / f"{point.replace('.', '_')}-{occurrence}-{torn}"
                    if self._crash_trial(
                        str(root), point, occurrence, torn
                    ) is not None:
                        ran += 1
        assert ran >= len(CRASH_POINTS)  # every point actually fired

    def test_torn_append_leaves_a_detectable_tail(self, tmp_path):
        def hook(p: str) -> None:
            if p == "append":
                hook.count += 1
                if hook.count == 4:
                    raise SimulatedCrash(p, torn_bytes=11)

        hook.count = 0
        live = DurableEngine(
            _fresh_engine(b"torn-tail-node\x00\x00\x00\x00\x00\x00"),
            str(tmp_path),
            fsync_policy="off",
            crash_hook=hook,
        )
        rng = random.Random(5)
        try:
            for _ in range(10):
                _run_workload(live, rng, n_ops=1)
        except SimulatedCrash:
            pass
        surviving = scan(str(tmp_path))
        assert surviving.torn
        assert surviving.torn_bytes == 11
        # The abandoned writer released its flock: a fresh writer opens
        # the directory, truncates the torn tail, and serves appends.
        recovered = DurableEngine(
            _fresh_engine(b"torn-tail-node\x00\x00\x00\x00\x00\x00"),
            str(tmp_path),
            fsync_policy="off",
        )
        stats = recovered.recover()
        assert stats.records_applied == len(surviving.records)
        recovered.close()


try:
    import hypothesis  # noqa: F401

    _HAS_HYPOTHESIS = True
except ImportError:
    _HAS_HYPOTHESIS = False

if _HAS_HYPOTHESIS:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    class TestCrashPointProperty:
        """Hypothesis sweep over (crash point, occurrence, torn bytes,
        workload seed): the prefix-recovery property of
        TestCrashPointMatrix must hold everywhere in the space."""

        @settings(max_examples=12, deadline=None)
        @given(
            point=st.sampled_from(CRASH_POINTS),
            occurrence=st.integers(min_value=1, max_value=5),
            torn=st.integers(min_value=0, max_value=40),
            seed=st.integers(min_value=0, max_value=2**16),
        )
        def test_recovery_is_an_op_prefix(
            self, point, occurrence, torn, seed, tmp_path_factory
        ):
            root = tmp_path_factory.mktemp("crashprop")
            TestCrashPointMatrix()._crash_trial(
                str(root),
                point,
                occurrence,
                torn if point == "append" else 0,
                seed=seed,
            )


class TestTieredCompactionInterplay:
    """Demotion × checkpoint/compaction: a checkpoint taken while
    sessions sleep in the demoted tier covers them (save_to_storage
    reads through the tier), so compacting to the watermark and
    crashing must recover them byte-identical — and a late vote on a
    recovered formerly-demoted session still applies."""

    def _decided_proposal(self, durable, scope, rng, t):
        request = CreateProposalRequest(
            name=f"d{rng.randrange(1 << 30)}",
            payload=os.urandom(rng.randrange(0, 12)),
            proposal_owner=b"owner",
            expected_voters_count=1,  # unanimity: one vote decides
            expiration_timestamp=50,
            liveness_criteria_yes=True,
        )
        proposal = durable.create_proposal(scope, request, t)
        chain = proposal.clone()
        signer = StubConsensusSigner(os.urandom(20))
        vote = build_vote(chain, True, signer, t)
        durable.process_incoming_vote(scope, vote, t)
        return proposal

    def test_demote_checkpoint_compact_crash_recover(self, tmp_path):
        rng = random.Random(0x7157)
        identity = os.urandom(20)
        durable = DurableEngine(
            _fresh_engine(identity),
            str(tmp_path / "wal"),
            fsync_policy="off",
            segment_bytes=512,
        )
        pids = []
        for k in range(6):
            proposal = self._decided_proposal(durable, f"s{k % 2}", rng, NOW)
            pids.append((f"s{k % 2}", proposal.proposal_id))
        # Demote half of them (unlogged by design: the tier is a cache).
        for scope, pid in pids[:3]:
            assert durable.demote_session(scope, pid) is True
        fp_live = state_fingerprint(durable)
        assert durable.occupancy()["tier_sessions"] == 3

        # Checkpoint + compact at the watermark: the snapshot must carry
        # the demoted sessions, because compaction deletes the only other
        # copy of their history.
        storage = InMemoryConsensusStorage()
        durable.checkpoint(storage, compact=True)
        assert len(list_segments(str(tmp_path / "wal"))) == 1

        # Traffic after the checkpoint, then kill -9.
        late = self._decided_proposal(durable, "s0", rng, NOW + 1)
        pids.append(("s0", late.proposal_id))
        fp_pre_crash = state_fingerprint(durable)
        durable.abandon()

        recovered = DurableEngine(
            _fresh_engine(identity), str(tmp_path / "wal"), fsync_policy="off"
        )
        stats = recovered.recover(storage)
        assert not stats.errors and stats.segments_dropped == 0
        # Byte-identical state: the demoted sessions came back through
        # the snapshot (as live sessions — the tier is a cache, and the
        # order-insensitive fingerprint cannot tell).
        assert state_fingerprint(recovered) == fp_pre_crash
        assert fp_pre_crash != fp_live  # the post-checkpoint traffic counts

        # A late vote on a formerly-demoted (recovered) session applies.
        scope, pid = pids[0]
        session = recovered.export_session(scope, pid)
        assert session.state.is_reached
        chain = recovered.get_proposal(scope, pid)
        extra = build_vote(chain, False, StubConsensusSigner(b"\x77" * 20), NOW + 2)
        statuses = recovered.ingest_votes([(scope, extra)], NOW + 2)
        assert int(statuses[0]) == 28  # ALREADY_REACHED: absorbed late vote
        recovered.close()

    def test_standalone_lifecycle_sweep_is_logged(self, tmp_path):
        """lifecycle_sweep outside sweep_timeouts GCs sessions — that is
        semantic, so the wrapper logs it (KIND_LIFECYCLE) and replay
        re-runs it: a crash must not resurrect GC'd sessions."""
        rng = random.Random(0xC0)
        identity = os.urandom(20)
        durable = DurableEngine(
            _fresh_engine(identity), str(tmp_path), fsync_policy="off"
        )
        durable.set_scope_config(
            "s0", ScopeConfig(demote_after=5.0, evict_decided_after=10.0)
        )
        proposal = self._decided_proposal(durable, "s0", rng, NOW)
        out = durable.lifecycle_sweep(NOW + 7)
        assert out["demoted"] == 1
        out = durable.lifecycle_sweep(NOW + 30)
        assert out["gc_tier"] == 1
        fp = state_fingerprint(durable)
        durable.abandon()

        recovered = DurableEngine(
            _fresh_engine(identity), str(tmp_path), fsync_policy="off"
        )
        stats = recovered.recover()
        assert not stats.errors
        assert state_fingerprint(recovered) == fp
        try:
            recovered.get_consensus_result("s0", proposal.proposal_id)
            raise AssertionError("GC'd session resurrected by replay")
        except SessionNotFound:
            pass
        recovered.close()

    def test_ttl_gc_exact_across_snapshot_restore(self, tmp_path):
        """The review scenario: a session DECIDED long after creation,
        checkpointed, then swept in the WAL tail at a clock where
        (now - created_at) >= TTL > (now - last_activity). The live
        engine keeps it (idle clock runs from the deciding vote); a
        recovered engine restores last_activity from the snapshot's
        created_at — so replay must apply the live run's logged GC
        OUTCOME (KIND_GC: empty here), never re-derive the policy, or
        it would collect a session the live engine still serves."""
        rng = random.Random(0x6C)
        identity = os.urandom(20)
        durable = DurableEngine(
            _fresh_engine(identity), str(tmp_path), fsync_policy="off"
        )
        durable.set_scope_config("s0", ScopeConfig(evict_decided_after=50.0))
        request = CreateProposalRequest(
            name="slowpoke",
            payload=b"x",
            proposal_owner=b"owner",
            expected_voters_count=1,
            expiration_timestamp=500,
            liveness_criteria_yes=True,
        )
        proposal = durable.create_proposal("s0", request, NOW)  # t0
        t_decide = NOW + 100
        vote = build_vote(
            proposal.clone(), True, StubConsensusSigner(os.urandom(20)), t_decide
        )
        durable.process_incoming_vote("s0", vote, t_decide)  # last activity
        storage = InMemoryConsensusStorage()
        durable.checkpoint(storage, compact=True)
        # Logged sweep at t3: t3 - t_decide < 50 <= t3 - t0 — live keeps it.
        t3 = NOW + 130
        out = durable.lifecycle_sweep(t3)
        assert out == {"demoted": 0, "gc_live": 0, "gc_tier": 0}
        assert durable.get_consensus_result("s0", proposal.proposal_id) is True
        fp = state_fingerprint(durable)
        durable.abandon()

        recovered = DurableEngine(
            _fresh_engine(identity), str(tmp_path), fsync_policy="off"
        )
        stats = recovered.recover(storage)
        assert not stats.errors
        assert state_fingerprint(recovered) == fp
        assert (
            recovered.get_consensus_result("s0", proposal.proposal_id) is True
        )
        recovered.close()
