"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead.

Note: the environment's sitecustomize imports jax at interpreter startup
and pins JAX_PLATFORMS=axon (the TPU tunnel), so env vars alone are too
late — we must go through jax.config before the backend initializes.
XLA_FLAGS is still read lazily at first backend init, so setting it here
works as long as no jax computation ran yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy cases tier-1 skips (-m 'not slow'); the device-crypto "
        "CI job and `pytest -m slow` run them",
    )
