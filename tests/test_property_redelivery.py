"""Property-based fuzz: amortized admission (verify cache + watermark)
vs the uncached engine as oracle.

The cache must change WHERE signature verification happens, never a
verdict: for any delivery sequence — growth, redelivery, duplicate-laden
batches, truncations, forks, corrupted signatures — a cache-on engine and
a cache-off engine must report identical statuses and end in identical
sessions. Hypothesis drives the sequence space far beyond the
hand-written smoke cases in test_redelivery.py.
"""

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from hashgraph_tpu import (
    CreateProposalRequest,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine, VerifiedVoteCache
from hashgraph_tpu.engine.verify_cache import _ENTRY_OVERHEAD

from common import NOW

N_SIGNERS = 6
SIGNERS = [StubConsensusSigner(bytes([i + 1]) * 20) for i in range(N_SIGNERS)]


def build_chain(n_votes: int):
    """A base proposal plus ``n_votes`` chain-linked stub votes."""
    maker = TpuConsensusEngine(
        StubConsensusSigner(b"\x42" * 20),
        capacity=4,
        voter_capacity=4,
        verify_cache=None,
    )
    proposal = maker.create_proposal(
        "s",
        CreateProposalRequest(
            name="p",
            payload=b"x",
            proposal_owner=b"o",
            expected_voters_count=N_SIGNERS * 2,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        ),
        NOW,
    )
    chain = proposal.clone()
    for i in range(n_votes):
        chain.votes.append(
            build_vote(chain, bool(i % 2), SIGNERS[i], NOW + 1 + i)
        )
    return proposal, chain


# One delivery op: (kind, k) — kind selects the surface, k the chain cut.
ops = st.lists(
    st.tuples(
        st.sampled_from(
            ["deliver", "deliver_batch", "votes", "corrupt", "fork"]
        ),
        st.integers(min_value=0, max_value=N_SIGNERS),
    ),
    min_size=1,
    max_size=8,
)


@settings(max_examples=25, deadline=None)
@given(n_votes=st.integers(min_value=1, max_value=N_SIGNERS), script=ops)
def test_cache_on_off_equivalence(n_votes, script):
    proposal, chain = build_chain(n_votes)

    def cut(k):
        p = chain.clone()
        p.votes = [v.clone() for v in chain.votes[: min(k, len(chain.votes))]]
        return p

    # Materialize every delivery payload ONCE, before the engine loop:
    # build_vote mints random vote ids, so a fork crafted per-engine would
    # differ between the two runs and the comparison would fuzz the
    # payload generator instead of the cache.
    deliveries = []
    for kind, k in script:
        if kind == "deliver":
            deliveries.append(("deliver", cut(k)))
        elif kind == "deliver_batch":
            # Same item twice in one batch: the second must settle as a
            # redelivery against the first's advanced watermark.
            deliveries.append(("deliver_batch", cut(k)))
        elif kind == "votes":
            deliveries.append(("votes", k))
        elif kind == "corrupt":
            bad = cut(max(k, 1))
            bad.votes[-1].signature = b"\x00" * 32
            deliveries.append(("deliver", bad))
        elif kind == "fork":
            forked = cut(max(k, 1))
            forked.votes[-1] = build_vote(
                proposal, True, StubConsensusSigner(b"\x90" * 20), NOW + 60
            )
            deliveries.append(("deliver", forked))

    outcomes = []
    for cache in ("default", None):
        engine = TpuConsensusEngine(
            StubConsensusSigner(b"\x52" * 20),
            capacity=8,
            voter_capacity=4,  # < expected: host substrate, fast under CPU
            verify_cache=cache,
        )
        log = []
        for kind, payload in deliveries:
            if kind == "deliver":
                log.append(
                    engine.deliver_proposal("s", payload.clone(), NOW + 20)
                )
            elif kind == "deliver_batch":
                log.append(
                    engine.deliver_proposals(
                        [("s", payload.clone()), ("s", payload.clone())],
                        NOW + 20,
                    )
                )
            elif kind == "votes":
                sub = engine.ingest_votes(
                    [("s", v.clone()) for v in chain.votes[:payload]],
                    NOW + 20,
                )
                log.append([int(s) for s in sub])
        try:
            session = engine.export_session("s", chain.proposal_id)
            final = (
                [v.vote_hash for v in session.proposal.votes],
                sorted(session.votes),
                session.state.kind,
                session.state.result,
            )
        except Exception as exc:  # session never registered
            final = repr(exc)
        outcomes.append((log, final))
    assert outcomes[0] == outcomes[1]


@settings(max_examples=50, deadline=None)
@given(
    max_entries=st.integers(min_value=1, max_value=16),
    use_byte_cap=st.booleans(),
    keys=st.lists(
        st.binary(min_size=1, max_size=48), min_size=1, max_size=80
    ),
)
def test_eviction_bounds_hold(max_entries, use_byte_cap, keys):
    max_bytes = (
        max_entries * (24 + _ENTRY_OVERHEAD) if use_byte_cap else None
    )
    cache = VerifiedVoteCache(max_entries=max_entries, max_bytes=max_bytes)
    for i, key in enumerate(keys):
        cache.put(key, bool(i % 2))
        assert len(cache) <= max_entries
        if max_bytes is not None:
            # A single oversized entry is allowed to stand alone; beyond
            # that the byte cap holds.
            assert cache.bytes_used <= max_bytes or len(cache) == 1
    # Every retained entry still serves its verdict.
    from hashgraph_tpu.engine.verify_cache import MISS

    served = sum(1 for key in set(keys) if cache.get(key) is not MISS)
    assert served == len(cache)
