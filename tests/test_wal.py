"""WAL primitives: framing, segments, writer policies, tail repair,
compaction, and the DurableEngine wrapper's logging discipline."""

import os

import pytest

from hashgraph_tpu import (
    ConsensusConfig,
    CreateProposalRequest,
    InMemoryConsensusStorage,
    NetworkType,
    ScopeConfig,
    build_vote,
)
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.tracing import Tracer
from hashgraph_tpu.wal import WalWriter, replay, scan
from hashgraph_tpu.wal import format as F
from hashgraph_tpu.wal.durable import DurableEngine
from hashgraph_tpu.wal.segment import base_lsn_of, list_segments, segment_name

from common import NOW, random_stub_signer


def request(n=3, name="p", exp=1000, liveness=True):
    return CreateProposalRequest(
        name=name,
        payload=b"x",
        proposal_owner=b"o",
        expected_voters_count=n,
        expiration_timestamp=exp,
        liveness_criteria_yes=liveness,
    )


class TestFormat:
    def test_record_roundtrip(self):
        frame = F.encode_record(7, F.KIND_SWEEP, F.encode_sweep(NOW))
        records, end = F.scan_buffer(frame)
        assert records == [(7, F.KIND_SWEEP, F.encode_sweep(NOW))]
        assert end == len(frame)

    def test_scan_stops_at_corrupt_crc(self):
        good = F.encode_record(1, F.KIND_SWEEP, F.encode_sweep(1))
        bad = bytearray(F.encode_record(2, F.KIND_SWEEP, F.encode_sweep(2)))
        bad[-1] ^= 0xFF  # flip a payload byte -> CRC mismatch
        records, end = F.scan_buffer(good + bytes(bad))
        assert [lsn for lsn, _, _ in records] == [1]
        assert end == len(good)

    def test_scan_stops_at_short_frame(self):
        good = F.encode_record(1, F.KIND_SWEEP, F.encode_sweep(1))
        torn = F.encode_record(2, F.KIND_SWEEP, F.encode_sweep(2))[:-3]
        records, end = F.scan_buffer(good + torn)
        assert len(records) == 1 and end == len(good)

    def test_scope_roundtrip(self):
        for scope in ["alpha", b"\x00\xffraw", 0, 123456789, -5, True]:
            blob = F.encode_scope(scope)
            decoded = F.decode_scope(F.Reader(blob))
            assert decoded == (int(scope) if isinstance(scope, bool) else scope)

    def test_scope_rejects_non_canonical(self):
        with pytest.raises(TypeError):
            F.encode_scope(("tuple", "scope"))

    def test_scope_config_roundtrip(self):
        config = ScopeConfig(
            network_type=NetworkType.P2P,
            default_consensus_threshold=0.9,
            default_timeout=30.0,
            default_liveness_criteria_yes=False,
            max_rounds_override=7,
        )
        out = F.decode_scope_config(F.Reader(F.encode_scope_config(config)))
        assert out == config
        config.max_rounds_override = None
        out = F.decode_scope_config(F.Reader(F.encode_scope_config(config)))
        assert out.max_rounds_override is None

    def test_consensus_config_roundtrip(self):
        config = ConsensusConfig(
            consensus_threshold=0.75,
            consensus_timeout=12.5,
            max_rounds=9,
            use_gossipsub_rounds=False,
            liveness_criteria=False,
        )
        assert (
            F.decode_consensus_config(F.Reader(F.encode_consensus_config(config)))
            == config
        )

    def test_segment_names_sort(self):
        assert base_lsn_of(segment_name(42)) == 42
        assert base_lsn_of("not-a-segment.txt") is None
        assert segment_name(9) < segment_name(10) < segment_name(100)


class TestWriter:
    def test_append_scan_roundtrip(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            lsns = [wal.append(F.KIND_SWEEP, F.encode_sweep(NOW + i)) for i in range(5)]
        assert lsns == [1, 2, 3, 4, 5]
        result = scan(str(tmp_path))
        assert [lsn for lsn, _, _ in result.records] == lsns
        assert not result.torn
        assert result.last_lsn == 5

    def test_reopen_continues_lsns(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            wal.append(F.KIND_SWEEP, F.encode_sweep(1))
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            assert wal.last_lsn == 1
            assert wal.append(F.KIND_SWEEP, F.encode_sweep(2)) == 2

    def test_second_writer_rejected_while_first_live(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            wal.append(F.KIND_SWEEP, F.encode_sweep(1))
            with pytest.raises(ValueError, match="locked"):
                WalWriter(tmp_path, fsync_policy="off")
        # flock released on close: a successor opens normally.
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            assert wal.last_lsn == 1

    def test_rotation_and_cross_segment_scan(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off", segment_bytes=64) as wal:
            for i in range(20):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        segments = list_segments(str(tmp_path))
        assert len(segments) > 1
        # Segment base lsns tile the record range contiguously.
        result = scan(str(tmp_path))
        assert [lsn for lsn, _, _ in result.records] == list(range(1, 21))

    def test_torn_tail_repaired_on_open(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            for i in range(3):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        (path,) = [p for _, p in list_segments(str(tmp_path))]
        with open(path, "ab") as fh:
            fh.write(b"\x99\x07garbage-torn-tail")
        pre = scan(str(tmp_path))
        assert pre.torn and len(pre.records) == 3
        with WalWriter(tmp_path, fsync_policy="off") as wal:  # repairs
            assert wal.last_lsn == 3
            wal.append(F.KIND_SWEEP, F.encode_sweep(99))
        post = scan(str(tmp_path))
        assert not post.torn
        assert [lsn for lsn, _, _ in post.records] == [1, 2, 3, 4]

    def test_fsync_policies(self, tmp_path):
        tracer = Tracer(enabled=True)
        with WalWriter(
            tmp_path / "always", fsync_policy="always", tracer=tracer
        ) as wal:
            for i in range(4):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        per_record = tracer.counters()["wal.fsync"]
        assert per_record >= 4  # one per append (+ close)

        tracer = Tracer(enabled=True)
        with WalWriter(
            tmp_path / "batch", fsync_policy="batch", fsync_interval=3, tracer=tracer
        ) as wal:
            for i in range(7):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        batched = tracer.counters()["wal.fsync"]
        assert batched == 3  # lsn 3, lsn 6, close

        tracer = Tracer(enabled=True)
        with WalWriter(tmp_path / "off", fsync_policy="off", tracer=tracer) as wal:
            for i in range(7):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        assert tracer.counters()["wal.fsync"] == 1  # close only

        with pytest.raises(ValueError):
            WalWriter(tmp_path / "bad", fsync_policy="sometimes")

    def test_append_counters(self, tmp_path):
        tracer = Tracer(enabled=True)
        with WalWriter(tmp_path, fsync_policy="off", tracer=tracer) as wal:
            wal.append(F.KIND_SWEEP, F.encode_sweep(0))
        counters = tracer.counters()
        assert counters["wal.append_records"] == 1
        assert counters["wal.append_bytes"] > 0

    def test_compaction_drops_only_covered_sealed_segments(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off", segment_bytes=64) as wal:
            for i in range(20):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
            segments = list_segments(str(tmp_path))
            assert len(segments) >= 3
            # Cover everything up to the penultimate segment's records.
            watermark = segments[-1][0] - 1
            removed = wal.compact(watermark)
            assert removed == len(segments) - 1
            survivors = list_segments(str(tmp_path))
            assert [base for base, _ in survivors] == [segments[-1][0]]
            # Surviving records replay exactly the uncovered tail.
            result = scan(str(tmp_path))
            assert [lsn for lsn, _, _ in result.records] == list(
                range(segments[-1][0], 21)
            )


class TestDurableEngineLogging:
    def make(self, tmp_path, **wal_kwargs):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        wal_kwargs.setdefault("fsync_policy", "off")
        return DurableEngine(engine, tmp_path, **wal_kwargs)

    def test_one_record_per_mutator(self, tmp_path):
        durable = self.make(tmp_path)
        durable.scope("s").with_network_type(NetworkType.P2P).initialize()
        pid = durable.create_proposal("s", request(3), NOW).proposal_id
        durable.cast_vote("s", pid, True, NOW)
        kinds = [kind for _, kind, _ in scan(str(tmp_path)).records]
        assert kinds == [F.KIND_SCOPE_CONFIG, F.KIND_PROPOSALS, F.KIND_VOTES]

    def test_reads_do_not_log(self, tmp_path):
        durable = self.make(tmp_path)
        pid = durable.create_proposal("s", request(3), NOW).proposal_id
        before = len(scan(str(tmp_path)).records)
        durable.get_proposal("s", pid)
        durable.get_scope_stats("s")
        durable.get_consensus_result("s", pid)
        assert len(scan(str(tmp_path)).records) == before

    def test_rejected_call_still_replays_identically(self, tmp_path):
        from hashgraph_tpu import UserAlreadyVoted

        durable = self.make(tmp_path)
        pid = durable.create_proposal("s", request(3), NOW).proposal_id
        durable.cast_vote("s", pid, True, NOW)
        with pytest.raises(UserAlreadyVoted):
            durable.cast_vote("s", pid, True, NOW)
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        replay(str(tmp_path), fresh)
        session = fresh.export_session("s", pid)
        assert len(session.votes) == 1  # the duplicate stayed rejected

    def test_columnar_requires_wire_votes(self, tmp_path):
        import numpy as np

        durable = self.make(tmp_path)
        with pytest.raises(ValueError, match="wire_votes"):
            durable.ingest_columnar(
                "s",
                np.zeros(1, np.int64),
                np.zeros(1, np.int64),
                np.zeros(1, bool),
                NOW,
            )

    def test_columnar_rejected_rows_never_logged(self, tmp_path):
        """The live columnar call trusts the caller's columns; replay
        re-derives them from wire bytes with fresh gid interning. A row the
        engine rejected live (here: a bogus pid column entry whose wire
        bytes carry the REAL pid) must not reach the log, or replay would
        accept what the live engine dropped."""
        import numpy as np

        from hashgraph_tpu.errors import StatusCode

        durable = self.make(tmp_path)
        proposal = durable.create_proposal("s", request(4), NOW)
        votes = chained_votes(
            proposal, [random_stub_signer() for _ in range(2)], NOW + 1
        )
        gids = np.array([durable.voter_gid(v.vote_owner) for v in votes])
        pids = np.full(len(votes), proposal.proposal_id, np.int64)
        pids[1] = 999_999  # unknown pid -> row rejected live
        statuses = durable.ingest_columnar(
            "s",
            pids,
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=[v.encode() for v in votes],
        )
        assert statuses[0] == int(StatusCode.OK)
        assert statuses[1] != int(StatusCode.OK)
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        stats = replay(str(tmp_path), fresh)
        assert stats.votes_replayed == 1  # only the accepted row was logged
        assert len(
            fresh.export_session("s", proposal.proposal_id).votes
        ) == len(durable.export_session("s", proposal.proposal_id).votes)

    def test_delete_scope_replays(self, tmp_path):
        durable = self.make(tmp_path)
        durable.create_proposal("gone", request(3), NOW)
        durable.create_proposal("kept", request(3), NOW)
        durable.delete_scope("gone")
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        replay(str(tmp_path), fresh)
        assert fresh.get_scope_stats("gone").total_sessions == 0
        assert fresh.get_scope_stats("kept").total_sessions == 1

    def test_checkpoint_compacts_everything_covered(self, tmp_path):
        durable = self.make(tmp_path, segment_bytes=256)
        for i in range(12):
            durable.create_proposal("s", request(3, name=f"p{i}"), NOW + i)
        assert len(list_segments(str(tmp_path))) > 1
        storage = InMemoryConsensusStorage()
        saved = durable.checkpoint(storage)
        assert saved == 10  # per-scope LRU cap keeps the newest 10
        survivors = list_segments(str(tmp_path))
        # Everything pre-snapshot was sealed and dropped; the single
        # surviving (active) segment holds only the snapshot mark.
        assert len(survivors) == 1
        kinds = [kind for _, kind, _ in scan(str(tmp_path)).records]
        assert kinds == [F.KIND_SNAPSHOT]
        # Snapshot + empty tail recovers the full state. The live writer
        # must close first: the directory flock admits one writer at a time.
        expected_sessions = durable.get_scope_stats("s").total_sessions
        durable.close()
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        recovered = DurableEngine(fresh, tmp_path, fsync_policy="off")
        stats = recovered.recover(storage)
        assert stats.records_applied == 0
        assert recovered.get_scope_stats("s").total_sessions == expected_sessions

    def test_timeout_and_sweep_replay(self, tmp_path):
        durable = self.make(tmp_path)
        pid = durable.create_proposal(
            "s", request(4, liveness=False, exp=50), NOW
        ).proposal_id
        assert durable.handle_consensus_timeout("s", pid, NOW + 60) is False
        pid2 = durable.create_proposal(
            "s", request(4, exp=50, liveness=True), NOW
        ).proposal_id
        swept = durable.sweep_timeouts(NOW + 120)
        assert [(s, p) for s, p, _ in swept] == [("s", pid2)]
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        replay(str(tmp_path), fresh)
        assert fresh.get_consensus_result("s", pid) is False
        assert fresh.get_consensus_result("s", pid2) is True


def chained_votes(proposal, signers, now):
    """Chain-linked votes the way real peers build them: each vote links to
    the proposal's current tail."""
    votes = []
    ferry = proposal.clone()
    for i, signer in enumerate(signers):
        vote = build_vote(ferry, True, signer, now + i)
        ferry.votes.append(vote)
        votes.append(vote)
    return votes


class TestRecordBudget:
    """MAX_RECORD enforcement + DurableEngine batch splitting: an oversized
    record must be rejected BEFORE acknowledgment (a frame over the cap
    reads as a torn tail and would silently destroy everything after it),
    and oversized batches must split across records instead of hitting it."""

    def make(self, tmp_path, **kwargs):
        engine = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        kwargs.setdefault("fsync_policy", "off")
        return DurableEngine(engine, tmp_path, **kwargs)

    def test_oversize_append_rejected_before_ack(self, tmp_path, monkeypatch):
        monkeypatch.setattr(F, "MAX_RECORD", 1024)  # avoid a 64 MiB payload
        with WalWriter(tmp_path, fsync_policy="off") as wal:
            wal.append(F.KIND_SWEEP, F.encode_sweep(1))
            with pytest.raises(ValueError, match="MAX_RECORD"):
                wal.append(F.KIND_VOTES, b"x" * 2048)
            wal.append(F.KIND_SWEEP, F.encode_sweep(2))
        result = scan(str(tmp_path))
        # The rejected record left no trace: contiguous LSNs, no torn tail.
        assert [lsn for lsn, _, _ in result.records] == [1, 2]
        assert not result.torn

    def test_vote_batch_splits_across_records(self, tmp_path):
        durable = self.make(tmp_path / "live", record_budget=200)
        proposal = durable.create_proposal("s", request(6), NOW)
        votes = chained_votes(
            proposal, [random_stub_signer() for _ in range(4)], NOW + 1
        )
        durable.ingest_votes([("s", v) for v in votes], NOW + 10)
        records = scan(str(tmp_path / "live")).records
        vote_records = [r for r in records if r[1] == F.KIND_VOTES]
        assert len(vote_records) > 1  # the wave crossed the budget
        assert [lsn for lsn, _, _ in records] == list(range(1, len(records) + 1))
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        stats = replay(str(tmp_path / "live"), fresh)
        assert stats.errors == []
        assert stats.votes_replayed == 4
        assert len(fresh.export_session("s", proposal.proposal_id).votes) == len(
            durable.export_session("s", proposal.proposal_id).votes
        )

    def test_unloggable_create_rejected_before_apply(self, tmp_path, monkeypatch):
        """Locally-minted paths log AFTER applying (the wire bytes only
        exist then), so a create whose record could exceed MAX_RECORD must
        fail BEFORE the engine mutates — otherwise the live engine holds a
        proposal recovery can never reproduce."""
        monkeypatch.setattr(F, "MAX_RECORD", 2048)
        durable = self.make(tmp_path, record_budget=2048)
        big = CreateProposalRequest(
            name="big",
            payload=b"x" * 4096,
            proposal_owner=b"o",
            expected_voters_count=3,
            expiration_timestamp=1000,
            liveness_criteria_yes=True,
        )
        with pytest.raises(ValueError, match="too large to log"):
            durable.create_proposal("s", big, NOW)
        assert durable.get_scope_stats("s").total_sessions == 0  # no mutation
        assert scan(str(tmp_path)).records == []  # no record either

    def test_timeout_pid_not_masked(self):
        scope, pid, now = F.decode_timeout(
            F.encode_timeout("s", (1 << 32) + 5, NOW)
        )
        assert pid == (1 << 32) + 5  # replay re-raises SessionNotFound, not
        # a masked timeout against pid 5

    def test_mid_log_corruption_reported_in_replay_stats(self, tmp_path):
        with WalWriter(tmp_path, fsync_policy="off", segment_bytes=64) as wal:
            for i in range(12):
                wal.append(F.KIND_SWEEP, F.encode_sweep(i))
        segments = list_segments(str(tmp_path))
        assert len(segments) >= 3
        with open(segments[1][1], "r+b") as fh:  # corrupt a SEALED segment
            fh.seek(2)
            fh.write(b"\xff\xff")
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        stats = replay(str(tmp_path), fresh)
        assert stats.torn
        assert stats.torn_path == segments[1][1]
        assert stats.segments_dropped == len(segments) - 2

    def test_columnar_batch_splits_and_replays(self, tmp_path):
        import numpy as np

        durable = self.make(tmp_path / "live", record_budget=200)
        proposal = durable.create_proposal("s", request(4), NOW)
        votes = chained_votes(
            proposal, [random_stub_signer() for _ in range(3)], NOW + 1
        )
        gids = np.array([durable.voter_gid(v.vote_owner) for v in votes])
        durable.ingest_columnar(
            "s",
            np.full(len(votes), proposal.proposal_id, np.int64),
            gids,
            np.array([v.vote for v in votes]),
            NOW + 10,
            wire_votes=[v.encode() for v in votes],
        )
        records = scan(str(tmp_path / "live")).records
        col_records = [r for r in records if r[1] == F.KIND_COLUMNAR]
        assert len(col_records) > 1
        fresh = TpuConsensusEngine(
            random_stub_signer(), capacity=16, voter_capacity=8
        )
        stats = replay(str(tmp_path / "live"), fresh)
        assert stats.errors == []
        assert stats.votes_replayed == 3
        assert fresh.get_consensus_result(
            "s", proposal.proposal_id
        ) == durable.get_consensus_result("s", proposal.proposal_id)


class TestBridgeWal:
    def test_bridge_peer_recovers_after_restart(self, tmp_path):
        from hashgraph_tpu.bridge import protocol as P
        from hashgraph_tpu.bridge.server import BridgeServer
        import socket

        key = os.urandom(32)
        wal_dir = str(tmp_path)

        def rpc(sock, opcode, payload):
            sock.sendall(P.encode_frame(opcode, payload))
            status, cursor = P.read_frame(sock)
            assert status == P.STATUS_OK, status
            return cursor

        def add_peer_and_propose(create: bool):
            with BridgeServer(capacity=8, voter_capacity=8, wal_dir=wal_dir) as server:
                host, port = server.address
                with socket.create_connection((host, port)) as sock:
                    c = rpc(sock, P.OP_ADD_PEER, P.u8(32) + key)
                    peer_id = c.u32()
                    if create:
                        c = rpc(
                            sock,
                            P.OP_CREATE_PROPOSAL,
                            P.u32(peer_id)
                            + P.string("scope")
                            + P.u64(NOW)
                            + P.string("p")
                            + P.blob(b"payload")
                            + P.u32(3)
                            + P.u64(1000)
                            + P.u8(1),
                        )
                        pid = c.u32()
                        rpc(
                            sock,
                            P.OP_CAST_VOTE,
                            P.u32(peer_id)
                            + P.string("scope")
                            + P.u32(pid)
                            + P.u8(1)
                            + P.u64(NOW),
                        )
                        return pid
                    c = rpc(
                        sock,
                        P.OP_GET_STATS,
                        P.u32(peer_id) + P.string("scope"),
                    )
                    return (c.u32(), c.u32(), c.u32(), c.u32())

        add_peer_and_propose(create=True)
        # "Crash": the server went away; a new server + same key re-adds the
        # peer, whose WAL replays the proposal and vote.
        total, active, failed, reached = add_peer_and_propose(create=False)
        assert total == 1 and active == 1

    def test_same_run_readd_reuses_live_wal(self, tmp_path):
        """Re-ADD_PEER with the same key in ONE server run must reuse the
        live durable engine — a second WalWriter on the same directory
        would interleave duplicate LSNs under the first."""
        from hashgraph_tpu.bridge import protocol as P
        from hashgraph_tpu.bridge.server import BridgeServer
        import socket

        key = os.urandom(32)

        def rpc(sock, opcode, payload):
            sock.sendall(P.encode_frame(opcode, payload))
            status, cursor = P.read_frame(sock)
            assert status == P.STATUS_OK, status
            return cursor

        with BridgeServer(
            capacity=8, voter_capacity=8, wal_dir=str(tmp_path)
        ) as server:
            host, port = server.address
            with socket.create_connection((host, port)) as sock:
                peer_a = rpc(sock, P.OP_ADD_PEER, P.u8(32) + key).u32()
                rpc(
                    sock,
                    P.OP_CREATE_PROPOSAL,
                    P.u32(peer_a)
                    + P.string("scope")
                    + P.u64(NOW)
                    + P.string("p")
                    + P.blob(b"payload")
                    + P.u32(3)
                    + P.u64(1000)
                    + P.u8(1),
                )
                peer_b = rpc(sock, P.OP_ADD_PEER, P.u8(32) + key).u32()
                assert peer_b != peer_a
                # Same engine behind both peer ids: B sees A's proposal.
                c = rpc(sock, P.OP_GET_STATS, P.u32(peer_b) + P.string("scope"))
                assert c.u32() == 1  # total_sessions
        records = scan(
            str(tmp_path / ("peer-" + key_identity_hex(key)))
        ).records
        lsns = [lsn for lsn, _, _ in records]
        assert lsns == sorted(set(lsns))  # strictly increasing, no duplicates

    def test_keyless_peer_gets_no_wal(self, tmp_path):
        """A keyless ADD_PEER mints an identity that can never be
        re-presented, so wrapping it would only accumulate dead WAL dirs."""
        from hashgraph_tpu.bridge import protocol as P
        from hashgraph_tpu.bridge.server import BridgeServer
        import socket

        with BridgeServer(
            capacity=8, voter_capacity=8, wal_dir=str(tmp_path)
        ) as server:
            host, port = server.address
            with socket.create_connection((host, port)) as sock:
                sock.sendall(P.encode_frame(P.OP_ADD_PEER, P.u8(0)))
                status, _ = P.read_frame(sock)
                assert status == P.STATUS_OK
        assert os.listdir(str(tmp_path)) == []


def key_identity_hex(key: bytes) -> str:
    from hashgraph_tpu import EthereumConsensusSigner

    return EthereumConsensusSigner(key).identity().hex()
