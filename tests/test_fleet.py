"""ConsensusFleet end-to-end: scope-sharded engines over the virtual
8-device CPU mesh (conftest) — routing, the one-psum fleet tally,
per-shard WAL crash/recovery isolation, and elastic membership.
"""

import threading

import numpy as np
import pytest

from hashgraph_tpu import (
    CreateProposalRequest,
    ScopeConfigBuilder,
    StatusCode,
    StubConsensusSigner,
    build_vote,
)
from hashgraph_tpu.parallel import ConsensusFleet, ShardRecoveringError

NOW = 1_700_000_000


def signer_factory(k: int):
    return StubConsensusSigner(bytes([k + 1]) * 20)


def make_fleet(n_shards=4, wal_root=None, **kw):
    kw.setdefault("capacity_per_shard", 32)
    kw.setdefault("voter_capacity", 8)
    return ConsensusFleet(
        signer_factory, n_shards=n_shards, wal_root=wal_root, **kw
    )


def request(n=4, expiry=10_000, liveness=True):
    return CreateProposalRequest(
        name="p", payload=b"", proposal_owner=b"o",
        expected_voters_count=n, expiration_timestamp=expiry,
        liveness_criteria_yes=liveness,
    )


def scopes_covering_all_shards(fleet, per_shard=1, prefix="s"):
    """Deterministically probe scope names until every shard owns
    ``per_shard`` of them; returns {shard_id: [scopes]}."""
    got = {sid: [] for sid in fleet.shard_ids}
    i = 0
    while any(len(v) < per_shard for v in got.values()):
        scope = f"{prefix}{i}"
        i += 1
        sid = fleet.owner_of(scope)
        if len(got[sid]) < per_shard:
            got[sid].append(scope)
    return got


@pytest.fixture
def fleet():
    f = make_fleet()
    yield f
    f.close()


# ── Routing ────────────────────────────────────────────────────────────


def test_distinct_devices_per_shard(fleet):
    devices = [fleet.shard(sid).device for sid in fleet.shard_ids]
    assert len(set(devices)) == len(devices)


def test_columnar_multi_routes_and_stitches(fleet):
    by_shard = scopes_covering_all_shards(fleet, per_shard=2)
    scopes = [s for group in by_shard.values() for s in group]
    for s in scopes:
        fleet.set_scope_config(
            s, ScopeConfigBuilder().gossipsub_preset().build()
        )
    pids = {
        s: [p.proposal_id for p in fleet.create_proposals(s, [request()] * 3, NOW)]
        for s in scopes
    }
    owners = [bytes([9 + i]) * 20 for i in range(3)]
    sidx, cpids, cgids, cvals = [], [], [], []
    for k, s in enumerate(scopes):
        gids = [fleet.voter_gid(s, o) for o in owners]
        for pid in pids[s]:
            for g in gids:
                sidx.append(k)
                cpids.append(pid)
                cgids.append(g)
                cvals.append(True)
    # Shuffle rows so every shard's rows interleave — the router must
    # stitch statuses back into input order.
    rng = np.random.default_rng(5)
    order = rng.permutation(len(cpids))
    st = fleet.ingest_columnar_multi(
        scopes,
        np.array(sidx)[order],
        np.array(cpids)[order],
        np.array(cgids)[order],
        np.array(cvals, bool)[order],
        NOW,
    )
    assert (st == int(StatusCode.OK)).all()
    # 3 YES on n=4 at gossip default threshold (2/3): every session decided.
    for s in scopes:
        stats = fleet.get_scope_stats(s)
        assert stats.consensus_reached == 3, (s, stats.__dict__)
    # Unknown pid rows report SESSION_NOT_FOUND in place.
    st2 = fleet.ingest_columnar_multi(
        scopes,
        np.zeros(1, np.int64),
        np.array([999_999], np.int64),
        np.zeros(1, np.int64),
        np.ones(1, bool),
        NOW,
    )
    assert st2.tolist() == [int(StatusCode.SESSION_NOT_FOUND)]


def test_single_scope_entry_points_route_to_owner(fleet):
    scope = "solo"
    sid = fleet.owner_of(scope)
    fleet.scope(scope).with_threshold(1.0).initialize()
    created = fleet.create_proposal(scope, request(n=2), NOW)
    # The session must live on the owning shard's engine, nowhere else.
    owner_engine = fleet.shard(sid).engine
    assert owner_engine.get_scope_stats(scope).total_sessions == 1
    for other in fleet.shard_ids:
        if other != sid:
            assert (
                fleet.shard(other).engine.get_scope_stats(scope).total_sessions
                == 0
            )
    st = fleet.ingest_columnar(
        scope,
        np.array([created.proposal_id], np.int64),
        np.array([fleet.voter_gid(scope, b"v" * 20)], np.int64),
        np.ones(1, bool),
        NOW,
    )
    assert st.tolist() == [int(StatusCode.OK)]
    assert fleet.get_consensus_result(scope, created.proposal_id) is None


def test_ingest_votes_and_pipelined_route(fleet):
    by_shard = scopes_covering_all_shards(fleet, prefix="v")
    scopes = [g[0] for g in by_shard.values()]
    ferries = {}
    for s in scopes:
        fleet.scope(s).with_threshold(1.0).initialize()
        p = fleet.create_proposal(s, request(n=6), NOW)
        ferries[s] = fleet.get_proposal(s, p.proposal_id)
    signers = [StubConsensusSigner(bytes([40 + i]) * 20) for i in range(4)]

    def batch_for(round_idx):
        items = []
        for s in scopes:
            ferry = ferries[s]
            v = build_vote(ferry, True, signers[round_idx], NOW + 1)
            ferry.votes.append(v)
            items.append((s, v))
        return items

    st = fleet.ingest_votes(batch_for(0), NOW + 2, pre_validated=True)
    assert (st == int(StatusCode.OK)).all()
    batches = [batch_for(1), batch_for(2), batch_for(3)]
    results = fleet.ingest_votes_pipelined(batches, NOW + 3, pre_validated=True)
    assert len(results) == 3
    for st in results:
        assert (st == int(StatusCode.OK)).all()


def test_deliver_proposals_watermark_per_shard(fleet):
    """Growing-chain redelivery through the router: each shard's
    validated-chain watermark behaves exactly like the engine's."""
    by_shard = scopes_covering_all_shards(fleet, prefix="d")
    scopes = [g[0] for g in by_shard.values()][:2]
    for s in scopes:
        fleet.scope(s).with_threshold(1.0).initialize()
    bases = {s: fleet.create_proposal(s, request(n=8), NOW) for s in scopes}
    signers = [StubConsensusSigner(bytes([60 + i]) * 20) for i in range(3)]
    chains = {}
    for s in scopes:
        chain = bases[s].clone()
        for k, signer in enumerate(signers):
            chain.votes.append(build_vote(chain, bool(k % 2), signer, NOW + 1 + k))
        chains[s] = chain
    for length in range(1, len(signers) + 1):
        items = []
        for s in scopes:
            grown = chains[s].clone()
            grown.votes = [v.clone() for v in chains[s].votes[:length]]
            items.append((s, grown))
        codes = fleet.deliver_proposals(items, NOW + 50)
        assert codes == [int(StatusCode.OK)] * len(items), (length, codes)
    # Full redelivery settles crypto-free as ALREADY_EXIST on every shard.
    codes = fleet.deliver_proposals(
        [(s, chains[s].clone()) for s in scopes], NOW + 50
    )
    assert codes == [int(StatusCode.PROPOSAL_ALREADY_EXIST)] * len(scopes)


# ── Fleet tally / breakdown ────────────────────────────────────────────


def test_fleet_state_counts_psum_matches_host_mirrors(fleet):
    from hashgraph_tpu.ops.decide import STATE_ACTIVE, STATE_FREE

    by_shard = scopes_covering_all_shards(fleet, prefix="t")
    total = 0
    for group in by_shard.values():
        s = group[0]
        fleet.scope(s).with_threshold(1.0).initialize()
        fleet.create_proposals(s, [request(n=4)] * 2, NOW)
        total += 2
    # Device-psum path engaged (distinct devices) and equal to the host sum.
    assert fleet._tally() is not None
    counts = fleet.fleet_state_counts()
    host = {}
    for sid in fleet.shard_ids:
        for code, c in fleet.shard(sid).pool().state_counts().items():
            host[code] = host.get(code, 0) + c
    for code, c in host.items():
        assert counts.get(code, 0) == c, (code, counts, host)
    assert counts[STATE_ACTIVE] == total
    assert counts[STATE_FREE] == 32 * 4 - total


def test_occupancy_and_health_breakdown(fleet):
    by_shard = scopes_covering_all_shards(fleet, prefix="o")
    for group in by_shard.values():
        s = group[0]
        fleet.scope(s).with_threshold(1.0).initialize()
        fleet.create_proposal(s, request(), NOW)
    occ = fleet.occupancy()
    assert set(occ) == set(fleet.shard_ids)
    for sid, entry in occ.items():
        assert entry["live_sessions"] == 1
        assert entry["device_slots_used"] == 1
        assert entry["capacity"] == 32
        assert sum(entry["per_device_slots_used"]) == 1
    health = fleet.health_report(NOW)
    assert set(health) == set(fleet.shard_ids)
    for rep in health.values():
        assert "peers" in rep and "alerts" in rep


# ── Elastic membership ─────────────────────────────────────────────────


def test_pinned_scopes_survive_add_shard(fleet):
    by_shard = scopes_covering_all_shards(fleet, per_shard=2, prefix="e")
    live = {}
    for group in by_shard.values():
        s = group[0]
        fleet.scope(s).with_threshold(1.0).initialize()
        p = fleet.create_proposal(s, request(), NOW)
        live[s] = (fleet.owner_of(s), p.proposal_id)
    new_sid = fleet.add_shard()
    assert new_sid in fleet.shard_ids and fleet.n_shards == 5
    # Every LIVE scope still routes to the shard holding its sessions.
    for s, (sid, pid) in live.items():
        assert fleet.owner_of(s) == sid
        assert fleet.get_proposal(s, pid).proposal_id == pid
    # New scopes can land on the new shard (rendezvous steals ~1/5).
    stolen = [
        f"fresh{i}" for i in range(100)
        if fleet.owner_of(f"fresh{i}") == new_sid
    ]
    assert stolen, "new shard never wins placement"
    s = stolen[0]
    fleet.scope(s).with_threshold(1.0).initialize()
    p = fleet.create_proposal(s, request(), NOW)
    assert (
        fleet.shard(new_sid).engine.get_scope_stats(s).total_sessions == 1
    )
    # Removing a shard with live pinned scopes is refused without force.
    pinned_sid = next(iter(live.values()))[0]
    with pytest.raises(ValueError, match="live scopes"):
        fleet.remove_shard(pinned_sid)
    # delete_scope releases the pin; a drained shard removes cleanly.
    fleet.delete_scope(s)
    fleet.remove_shard(new_sid)
    assert fleet.n_shards == 4


# ── Crash / recovery isolation ─────────────────────────────────────────


def _build_wal_traffic(fleet, scope, n_votes=4):
    fleet.scope(scope).with_threshold(1.0).initialize()
    p = fleet.create_proposal(scope, request(n=n_votes + 2), NOW)
    ferry = fleet.get_proposal(scope, p.proposal_id)
    items = []
    for i in range(n_votes):
        v = build_vote(
            ferry, True, StubConsensusSigner(bytes([80 + i]) * 20), NOW + 1 + i
        )
        ferry.votes.append(v)
        items.append((scope, v))
    st = fleet.ingest_votes(items, NOW + 10, pre_validated=True)
    assert (st == int(StatusCode.OK)).all()
    return p.proposal_id


def test_recovery_does_not_stall_other_shards(tmp_path):
    """THE isolation contract: killing + WAL-replaying one shard's engine
    must not stall ingest on the other shards. The replay is held
    mid-record via the on_record hook while the test drives real traffic
    through every other shard and asserts it completes."""
    fleet = make_fleet(n_shards=3, wal_root=str(tmp_path))
    try:
        by_shard = scopes_covering_all_shards(fleet, prefix="r")
        victim_sid = fleet.shard_ids[0]
        victim_scope = by_shard[victim_sid][0]
        victim_pid = _build_wal_traffic(fleet, victim_scope)
        survivors = {
            sid: group[0]
            for sid, group in by_shard.items()
            if sid != victim_sid
        }
        ferries = {}
        for s in survivors.values():
            fleet.scope(s).with_threshold(1.0).initialize()
            p = fleet.create_proposal(s, request(n=8), NOW)
            ferries[s] = fleet.get_proposal(s, p.proposal_id)

        fleet.crash_shard(victim_sid)
        gate, release = threading.Event(), threading.Event()

        def on_record(lsn, kind):
            gate.set()
            assert release.wait(timeout=60), "test released the replay late"

        thread = fleet.recover_shard(
            victim_sid, background=True, on_record=on_record
        )
        try:
            assert gate.wait(timeout=60), "replay never reached a record"
            # Replay is BLOCKED mid-record. Other shards must serve, both
            # scalar and columnar:
            items = []
            for s, ferry in ferries.items():
                v = build_vote(
                    ferry, True, StubConsensusSigner(b"x" * 20), NOW + 20
                )
                ferry.votes.append(v)
                items.append((s, v))
            st = fleet.ingest_votes(items, NOW + 21, pre_validated=True)
            assert (st == int(StatusCode.OK)).all()
            # The recovering shard's scopes fail fast (no deadlock/stall)...
            with pytest.raises(ShardRecoveringError):
                fleet.get_scope_stats(victim_scope)
            # ...and batch routers either raise or mark rows NOT_FOUND.
            some_scope = next(iter(survivors.values()))
            with pytest.raises(ShardRecoveringError):
                fleet.ingest_columnar_multi(
                    [victim_scope, some_scope],
                    np.zeros(1, np.int64),
                    np.array([victim_pid], np.int64),
                    np.zeros(1, np.int64),
                    np.ones(1, bool),
                    NOW + 22,
                )
            st = fleet.ingest_columnar_multi(
                [victim_scope],
                np.zeros(1, np.int64),
                np.array([victim_pid], np.int64),
                np.zeros(1, np.int64),
                np.ones(1, bool),
                NOW + 22,
                unavailable_ok=True,
            )
            assert st.tolist() == [int(StatusCode.SESSION_NOT_FOUND)]
            # Fleet-wide readouts must keep working mid-recovery (host
            # fallback over the SERVING shards — no crash on the crashed
            # shard's dropped engine).
            counts = fleet.fleet_state_counts()
            assert sum(counts.values()) == 32 * 2  # two serving shards
            assert fleet.occupancy()[victim_sid]["recovering"] is True
        finally:
            release.set()
        thread.join(timeout=120)
        assert not thread.is_alive()
        # Recovered shard serves again with its pre-crash state intact.
        assert fleet.shard(victim_sid).available
        stats = fleet.get_scope_stats(victim_scope)
        assert stats.total_sessions == 1
        assert len(fleet.get_proposal(victim_scope, victim_pid).votes) == 4
    finally:
        fleet.close()


def test_recover_foreground_roundtrip(tmp_path):
    fleet = make_fleet(n_shards=2, wal_root=str(tmp_path))
    try:
        scope = scopes_covering_all_shards(fleet, prefix="f")[
            fleet.shard_ids[1]
        ][0]
        pid = _build_wal_traffic(fleet, scope, n_votes=3)
        before = fleet.get_scope_stats(scope).__dict__
        fleet.crash_shard(fleet.shard_ids[1])
        assert not fleet.shard(fleet.shard_ids[1]).available
        fleet.recover_shard(fleet.shard_ids[1])
        assert fleet.get_scope_stats(scope).__dict__ == before
        # Post-recovery the shard takes NEW traffic (watermark replay
        # left the chain extendable).
        ferry = fleet.get_proposal(scope, pid)
        v = build_vote(ferry, True, StubConsensusSigner(b"y" * 20), NOW + 30)
        st = fleet.ingest_votes([(scope, v)], NOW + 31, pre_validated=True)
        assert st.tolist() == [int(StatusCode.OK)]
    finally:
        fleet.close()


def test_close_releases_every_shard_wal(tmp_path):
    """fleet.close() must actually close each DurableEngine (flush +
    release the directory flock) — regression for the dead
    ``callable(wal)`` guard (``wal`` is a property returning a WalWriter,
    never callable): a new writer on the same directory must succeed
    immediately after close."""
    from hashgraph_tpu.wal import WalWriter

    fleet = make_fleet(n_shards=2, wal_root=str(tmp_path))
    scope = scopes_covering_all_shards(fleet, prefix="c")[fleet.shard_ids[0]][0]
    _build_wal_traffic(fleet, scope, n_votes=2)
    wal_dirs = [fleet.shard(sid).wal_dir for sid in fleet.shard_ids]
    fleet.close()
    for wal_dir in wal_dirs:
        with WalWriter(wal_dir) as wal:  # would raise on a held flock
            assert wal.directory == wal_dir


def test_delete_scope_evicts_placement_memo(fleet):
    scope = "churny"
    fleet.scope(scope).with_threshold(1.0).initialize()
    assert scope in fleet.placement._cache
    fleet.delete_scope(scope)
    assert scope not in fleet.placement._cache


def test_crash_without_wal_root_is_rejected(fleet):
    with pytest.raises(ValueError, match="wal_root"):
        fleet.crash_shard(fleet.shard_ids[0])


def test_recovery_rebuilds_pre_crash_identity_after_membership_change(
    tmp_path,
):
    """The recovery signer index is the shard's CONSTRUCTION index, not
    its current dict position: removing an earlier shard must not make a
    later shard recover with someone else's identity."""
    fleet = make_fleet(n_shards=3, wal_root=str(tmp_path))
    try:
        victim = fleet.shard_ids[2]
        identity_before = fleet.shard(victim).engine.signer().identity()
        assert identity_before == signer_factory(2).identity()
        fleet.remove_shard(fleet.shard_ids[0])  # reshuffles dict positions
        fleet.crash_shard(victim)
        fleet.recover_shard(victim)
        assert (
            fleet.shard(victim).engine.signer().identity() == identity_before
        )
        # add_shard after a removal mints a FRESH index (never reuses 0).
        new_sid = fleet.add_shard()
        new_identity = fleet.shard(new_sid).engine.signer().identity()
        taken = {
            fleet.shard(sid).engine.signer().identity()
            for sid in fleet.shard_ids
            if sid != new_sid
        }
        assert new_identity not in taken
    finally:
        fleet.close()


def test_failed_background_recovery_is_surfaced_and_retryable(tmp_path):
    fleet = make_fleet(n_shards=2, wal_root=str(tmp_path))
    try:
        victim = fleet.shard_ids[0]
        scope = scopes_covering_all_shards(fleet, prefix="fb")[victim][0]
        _build_wal_traffic(fleet, scope, n_votes=2)
        fleet.crash_shard(victim)

        def exploding(lsn, kind):
            raise RuntimeError("disk went away")

        thread = fleet.recover_shard(
            victim, background=True, on_record=exploding
        )
        thread.join(timeout=60)
        assert not thread.is_alive()
        shard = fleet.shard(victim)
        assert not shard.available  # still down, not half-recovered
        assert isinstance(shard.recovery_error, RuntimeError)
        assert "disk went away" in fleet.occupancy()[victim]["recovery_error"]
        assert (
            "disk went away" in fleet.health_report(NOW)[victim]["recovery_error"]
        )
        # Retry without the fault: recovers cleanly, error cleared.
        fleet.recover_shard(victim)
        assert shard.available and shard.recovery_error is None
        assert fleet.get_scope_stats(scope).total_sessions == 1
    finally:
        fleet.close()
