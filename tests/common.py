"""Helpers shared by the service-level test suites
(reference: tests/common/mod.rs).

Multi-peer behavior is tested in-process: services share storage/event bus
and messages are hand-delivered, exactly as the reference does. Time is
synthetic — every API takes caller-supplied ``now`` so tests advance the
clock arithmetically instead of sleeping.
"""

from __future__ import annotations

import os

from hashgraph_tpu import (
    BroadcastEventBus,
    ConsensusService,
    EthereumConsensusSigner,
    InMemoryConsensusStorage,
    Proposal,
    StubConsensusSigner,
    Vote,
    build_vote,
)

NOW = 1_700_000_000  # fixed synthetic "current time" base


def now_ts() -> int:
    return NOW


def random_stub_signer() -> StubConsensusSigner:
    return StubConsensusSigner(os.urandom(20))


def make_service(scheme: str = "stub", max_sessions: int = 10) -> ConsensusService:
    """Fresh service with in-memory storage + broadcast bus.

    ``scheme="stub"`` keeps suites fast; ``scheme="ethereum"`` exercises real
    ECDSA (used by crypto-sensitive suites).
    """
    signer = (
        random_stub_signer() if scheme == "stub" else EthereumConsensusSigner.random()
    )
    return ConsensusService(
        InMemoryConsensusStorage(), BroadcastEventBus(), signer, max_sessions
    )


def sibling_service(service: ConsensusService, scheme: str = "stub") -> ConsensusService:
    """Another peer's view: same storage + bus, its own signer."""
    signer = (
        random_stub_signer() if scheme == "stub" else EthereumConsensusSigner.random()
    )
    return ConsensusService(service.storage(), service.event_bus(), signer)


def cast_remote_vote(service, scope, proposal_id, choice, signer, now=NOW) -> Vote:
    """Build + deliver a vote as if from a remote peer
    (reference: tests/common/mod.rs:44-55)."""
    proposal = service.storage().get_proposal(scope, proposal_id)
    vote = build_vote(proposal, choice, signer, now)
    service.process_incoming_vote(scope, vote.clone(), now)
    return vote


def cast_remote_vote_and_get_proposal(
    service, scope, proposal_id, choice, signer, now=NOW
) -> Proposal:
    cast_remote_vote(service, scope, proposal_id, choice, signer, now)
    return service.storage().get_proposal(scope, proposal_id)
